"""Elastic training under chaos: SIGKILL one worker mid-step and
assert the group survives with loss-curve continuity.

Two recovery paths, held to the SAME tolerance against an exact
locally-computed reference curve:

  * reshard (tier-1): the controller re-forms the ring at N-1, the
    survivors redistribute ZeRO optimizer shards over collectives
    (train/reshard.py) with the dead rank's segment reconstructed from
    its in-memory peer mirror — no step regression beyond the
    in-flight step, no storage touched;
  * checkpoint restore (slow): the classic teardown + restart from the
    latest per-step checkpoint.

Every rank sees the SAME batch, so the loss curve is world-size
independent — a 3-rank prefix and a 2-rank suffix must lie on one
reference trajectory if and only if state survived intact.

Own module (needs its own cluster + failure configs); late-alphabet
name keeps the tier-1 870 s cutoff stable."""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.config import Config
from ray_tpu.train.api import (Checkpoint, FailureConfig, RunConfig,
                               ScalingConfig)

pytestmark = pytest.mark.chaos

STEPS, DIE_AT, DIM, LR = 12, 5, 12, 0.05
TOL = dict(rtol=2e-3, atol=1e-4)     # the ONE continuity tolerance


def _problem():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(32, DIM)).astype(np.float32)
    w_true = np.linspace(-1.0, 1.0, DIM).astype(np.float32)
    return X, (X @ w_true).astype(np.float32)


def _loss_grad(w, X, y):
    r = X @ w - y
    return float(np.mean(r * r)), \
        ((2.0 / len(y)) * (X.T @ r)).astype(np.float32)


def _reference_losses():
    """The uninterrupted trajectory, computed exactly (adam is
    elementwise, so the sharded update reproduces it per coordinate)."""
    import optax
    X, y = _problem()
    opt = optax.adam(LR)
    w = np.zeros(DIM, np.float32)
    state = opt.init(w)
    losses = []
    for _ in range(STEPS):
        loss, g = _loss_grad(w, X, y)
        losses.append(loss)
        upd, state = opt.update(g, state, w)
        w = (w + np.asarray(upd, np.float32)).astype(np.float32)
    return losses


@pytest.fixture
def cluster():
    cfg = Config.from_env(num_workers_prestart=0, max_workers_per_node=8,
                          default_max_task_retries=0)
    ray_tpu.init(num_cpus=6, config=cfg)
    yield
    ray_tpu.shutdown()


def test_chaos_kill_midstep_reshards_to_n_minus_1(cluster, tmp_path):
    marker = os.path.join(str(tmp_path), "died_once")
    problem, loss_grad = _problem, _loss_grad
    steps_n, die_at, dim, lr = STEPS, DIE_AT, DIM, LR

    def train_fn():
        import os as _os
        import signal as _signal
        import time as _time

        import numpy as _np
        import optax

        from ray_tpu import train as _train
        ctx = _train.get_context()
        X, y = problem()
        params = {"w": _np.zeros(dim, _np.float32)}
        opt = _train.ShardedOptimizer(optax.adam(lr),
                                      mirror_interval_steps=1)
        state = opt.init(params)
        step = 0
        while step < steps_n:
            loss, g = loss_grad(params["w"], X, y)
            if step == die_at and ctx.generation == 0 \
                    and ctx.get_world_rank() == 1 \
                    and not _os.path.exists(marker):
                open(marker, "w").close()
                # brief pause so the step-(die_at-1) mirror and at
                # least one controller poll land before the death —
                # mid-step: the survivors are about to enter the sync
                _time.sleep(0.5)
                _os.kill(_os.getpid(), _signal.SIGKILL)
            try:
                params, state = opt.update({"w": g}, state, params)
            except _train.PeerLostError:
                _train.await_regroup(timeout_s=60)
                state = opt.reshard(state)
                continue            # retry the interrupted step
            _train.report({"step": step, "loss": loss,
                           "world": ctx.get_world_size(),
                           "generation": ctx.generation})
            step += 1
            _time.sleep(0.15)       # paces mirrors + controller polls

    res = train.JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(
            num_workers=(2, 3), sync_timeout_s=8.0,
            elastic_grow_interval_s=0.0),
        run_config=RunConfig(
            failure_config=FailureConfig(max_failures=1))).fit()
    assert res.error is None, res.error
    assert os.path.exists(marker), "the victim never fired"
    hist = [m for m in res.metrics_history if "step" in m]
    steps = [m["step"] for m in hist]
    # continuity: every step reported exactly once, no regression
    # beyond the in-flight step (which simply retried)
    assert steps == list(range(STEPS)), steps
    worlds = [m["world"] for m in hist]
    assert set(worlds[:DIE_AT]) == {3}, worlds
    assert set(worlds[DIE_AT:]) == {2}, worlds
    assert hist[-1]["generation"] == 1          # resharded, no restart
    np.testing.assert_allclose(
        [m["loss"] for m in hist], _reference_losses(), **TOL)


def test_chaos_reshard_preserves_error_feedback_discipline(
        cluster, tmp_path):
    """int8+error-feedback gradient sync through the SAME mid-step kill:
    the quantization residual is nonzero while training (EF is live),
    provably dropped at the reshard (a residual accumulated against the
    3-rank split must never compensate 2-rank frames), and the job
    still completes one continuous trajectory at the codec's
    tolerance."""
    marker = os.path.join(str(tmp_path), "died_once")
    problem, loss_grad = _problem, _loss_grad
    steps_n, die_at, dim, lr = STEPS, DIE_AT, DIM, LR

    def train_fn():
        import os as _os
        import signal as _signal
        import time as _time

        import numpy as _np
        import optax

        from ray_tpu import train as _train
        ctx = _train.get_context()
        X, y = problem()
        params = {"w": _np.zeros(dim, _np.float32)}
        opt = _train.ShardedOptimizer(optax.adam(lr),
                                      grad_quantize="int8",
                                      error_feedback=True,
                                      mirror_interval_steps=1)
        state = opt.init(params)

        def resid():
            ef = opt._ef
            return float(_np.abs(ef.residual).max()) \
                if ef is not None and ef.residual is not None else -1.0

        step, resid_pre, dropped = 0, 0.0, 0
        while step < steps_n:
            loss, g = loss_grad(params["w"], X, y)
            if step == die_at and ctx.generation == 0 \
                    and ctx.get_world_rank() == 1 \
                    and not _os.path.exists(marker):
                open(marker, "w").close()
                _time.sleep(0.5)
                _os.kill(_os.getpid(), _signal.SIGKILL)
            try:
                params, state = opt.update({"w": g}, state, params)
            except _train.PeerLostError:
                resid_pre = resid()     # accumulated against 3 ranks
                _train.await_regroup(timeout_s=60)
                state = opt.reshard(state)
                # reshard() must have invalidated the accumulator
                dropped = int(opt._ef is None or opt._ef.residual is None)
                continue
            _train.report({"step": step, "loss": loss,
                           "world": ctx.get_world_size(),
                           "generation": ctx.generation,
                           "resid": resid(), "resid_pre": resid_pre,
                           "resid_dropped": dropped})
            step += 1
            _time.sleep(0.15)

    res = train.JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(
            num_workers=(2, 3), sync_timeout_s=8.0,
            elastic_grow_interval_s=0.0),
        run_config=RunConfig(
            failure_config=FailureConfig(max_failures=1))).fit()
    assert res.error is None, res.error
    assert os.path.exists(marker), "the victim never fired"
    hist = [m for m in res.metrics_history if "step" in m]
    assert [m["step"] for m in hist] == list(range(STEPS))
    assert set(m["world"] for m in hist[DIE_AT:]) == {2}
    assert hist[-1]["generation"] == 1          # resharded, no restart
    # EF was live on the old split: residual nonzero both during the
    # 3-rank prefix and at the moment the peer died
    assert all(m["resid"] > 0 for m in hist[1:DIE_AT]), hist[:DIE_AT]
    assert hist[-1]["resid_pre"] > 0
    # ...and provably dropped at the reshard, then rebuilt at 2 ranks
    assert hist[-1]["resid_dropped"] == 1
    assert all(m["resid"] > 0 for m in hist[DIE_AT:]), hist[DIE_AT:]
    # loss continuity at the codec's tolerance: int8+EF tracks the
    # exact fp32 reference within the quantized sync's noise floor
    np.testing.assert_allclose(
        [m["loss"] for m in hist], _reference_losses(),
        rtol=0.05, atol=5e-3)


@pytest.mark.slow
def test_chaos_kill_midstep_checkpoint_restore_same_tolerance(
        cluster, tmp_path):
    """The fallback path under the SAME kill and the SAME tolerance:
    fixed-size group, per-step checkpoints, full restart + restore —
    proving the reshard test's tolerance is not doing hidden work."""
    tmp = str(tmp_path)
    marker = os.path.join(tmp, "died_once")
    problem, loss_grad = _problem, _loss_grad
    steps_n, die_at, dim, lr = STEPS, DIE_AT, DIM, LR

    def train_fn():
        import json as _json
        import os as _os
        import signal as _signal
        import time as _time

        import jax
        import numpy as _np
        import optax

        from ray_tpu import train as _train
        ctx = _train.get_context()
        rank = ctx.get_world_rank()
        X, y = problem()
        params = {"w": _np.zeros(dim, _np.float32)}
        opt = _train.ShardedOptimizer(optax.adam(lr))
        state = opt.init(params)
        start = 0
        resume = ctx.get_checkpoint()
        if resume is not None:
            d = resume.path
            with open(_os.path.join(d, "meta.json")) as f:
                start = _json.load(f)["step"] + 1
            params = {"w": _np.load(_os.path.join(d, "w.npy"))}
            blob = _np.load(_os.path.join(d, f"opt_{rank}.npz"))
            tdef = jax.tree_util.tree_structure(state)
            state = jax.tree_util.tree_unflatten(
                tdef, [blob[f"l{i}"] for i in range(len(blob.files))])
        for step in range(start, steps_n):
            loss, g = loss_grad(params["w"], X, y)
            if step == die_at and rank == 1 \
                    and not _os.path.exists(marker):
                open(marker, "w").close()
                _time.sleep(0.3)
                _os.kill(_os.getpid(), _signal.SIGKILL)
            params, state = opt.update({"w": g}, state, params)
            d = _os.path.join(tmp, f"ck_{step}")
            _os.makedirs(d, exist_ok=True)
            leaves = [_np.asarray(x) for x in
                      jax.tree_util.tree_leaves(state)]
            _np.savez(_os.path.join(d, f"opt_{rank}.npz"),
                      **{f"l{i}": a for i, a in enumerate(leaves)})
            if rank == 0:
                _np.save(_os.path.join(d, "w.npy"), params["w"])
                with open(_os.path.join(d, "meta.json"), "w") as f:
                    _json.dump({"step": step}, f)
                _train.report(
                    {"step": step, "loss": loss,
                     "world": ctx.get_world_size()},
                    checkpoint=_train.Checkpoint.from_directory(d))
            else:
                _train.report({"step": step, "loss": loss})

    res = train.JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=3, sync_timeout_s=8.0),
        run_config=RunConfig(
            storage_path=tmp,
            failure_config=FailureConfig(max_failures=1))).fit()
    assert res.error is None, res.error
    assert os.path.exists(marker), "the victim never fired"
    hist = [m for m in res.metrics_history if "step" in m]
    steps = [m["step"] for m in hist]
    assert steps == list(range(STEPS)), steps
    assert set(m["world"] for m in hist) == {3}
    np.testing.assert_allclose(
        [m["loss"] for m in hist], _reference_losses(), **TOL)


def test_failed_reshape_and_restart_are_one_incident(cluster, tmp_path):
    """A reshape the train_fn can't honor (no await_regroup loop: the
    survivor's next collective raises an uncaught PeerLostError) must
    escalate to the checkpoint restart WITHOUT consuming a second
    failure-budget unit — with max_failures=1 the job still completes.
    Double-charging (reshape + same-incident restart) would exhaust
    the budget and kill the job on a single preemption."""
    tmp = str(tmp_path)

    def train_fn():
        import os as _os
        import time as _time

        import numpy as _np

        from ray_tpu import train as _train
        ctx = _train.get_context()
        start = 0
        resume = ctx.get_checkpoint()
        if resume is not None:
            with open(_os.path.join(resume.path, "step.txt")) as f:
                start = int(f.read()) + 1
        for step in range(start, 8):
            if ctx.get_world_size() > 1:
                _train.allreduce_gradients(
                    {"g": _np.ones(4, _np.float32)})
            if ctx.get_world_rank() == 0:
                d = _os.path.join(tmp, f"ck_{step}")
                _os.makedirs(d, exist_ok=True)
                with open(_os.path.join(d, "step.txt"), "w") as f:
                    f.write(str(step))
                _train.report(
                    {"step": step},
                    checkpoint=_train.Checkpoint.from_directory(d))
            else:
                _train.report({"step": step})
            _time.sleep(0.2)
            if step == 3 and ctx.get_world_rank() == 1 and \
                    not _os.path.exists(_os.path.join(tmp, "death")):
                open(_os.path.join(tmp, "death"), "w").close()
                _os._exit(1)

    res = train.JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=(1, 2),
                                     sync_timeout_s=8.0),
        run_config=RunConfig(
            storage_path=tmp,
            failure_config=FailureConfig(max_failures=1))).fit()
    assert res.error is None, res.error
    assert res.metrics["step"] == 7
    assert os.path.exists(os.path.join(tmp, "death"))


def test_failure_budget_resets_after_clean_streak(cluster, tmp_path):
    """FailureConfig.reset_after_clean_reports: two rare incidents, one
    budget unit each — a cumulative budget (the old behavior) would
    exhaust max_failures=1 at the second death."""
    tmp = str(tmp_path)

    def train_fn():
        import os as _os
        import time as _time

        from ray_tpu import train as _train
        ctx = _train.get_context()
        start = 0
        resume = ctx.get_checkpoint()
        if resume is not None:
            with open(_os.path.join(resume.path, "step.txt")) as f:
                start = int(f.read()) + 1
        for step in range(start, 10):
            d = _os.path.join(tmp, f"ck_{step}")
            _os.makedirs(d, exist_ok=True)
            with open(_os.path.join(d, "step.txt"), "w") as f:
                f.write(str(step))
            _train.report({"step": step},
                          checkpoint=_train.Checkpoint.from_directory(d))
            # reports live in the worker until the controller's ~0.2 s
            # poll drains them — pace the loop, or a death would take
            # the whole clean streak down with it
            _time.sleep(0.3)
            if step in (2, 7) and \
                    not _os.path.exists(_os.path.join(
                        tmp, f"death_{step}")):
                open(_os.path.join(tmp, f"death_{step}"), "w").close()
                _os._exit(1)

    res = train.JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path=tmp,
            failure_config=FailureConfig(
                max_failures=1,
                reset_after_clean_reports=3))).fit()
    assert res.error is None, res.error
    assert res.metrics["step"] == 9
    deaths = [x for x in os.listdir(tmp) if x.startswith("death_")]
    assert len(deaths) == 2, deaths
