"""Hang & desync forensics (util/forensics.py): the per-rank
collective ledger, the cross-rank audit's culprit naming, the opt-in
pre-flight desync guard (Config.forensics_verify_level), the
controller stall watchdog, and postmortem bundles.

Tier-1, CPU. Thread-ring tests share ONE process-global ledger across
"ranks" (seqs interleave), so the audit's cross-rank semantics are
unit-tested on synthetic per-rank snapshots; the end-to-end watchdog
test uses real multi-process train workers, where each rank's ledger
is genuinely its own.

Named late in the alphabet ON PURPOSE: tier-1 is wall-clock bounded
(870s DOTS_PASSED cutoff) and new modules must not shift earlier
modules out of the window.
"""

import glob
import json
import os
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.config import Config, get_config
from ray_tpu.train.api import ScalingConfig
from ray_tpu.util import events, forensics

BUNDLE_DIR = tempfile.mkdtemp(prefix="fx_bundles_")


@pytest.fixture(autouse=True)
def _clean():
    forensics.reset()
    events.clear()
    yield
    forensics.reset()
    events.clear()


@pytest.fixture(scope="module")
def cluster():
    # The controller runs as a named ACTOR in its own worker process,
    # so the forensics knobs must ride the RAY_TPU_* env (inherited by
    # every spawned worker), not just the driver's Config object:
    # stall timeout dropped to 2s so the watchdog test fires in
    # seconds, forensics_dir pinned somewhere we can glob.
    env = {"RAY_TPU_FORENSICS_STALL_TIMEOUT_S": "2.0",
           "RAY_TPU_FORENSICS_DIR": BUNDLE_DIR}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    cfg = Config.from_env(num_workers_prestart=0, max_workers_per_node=8,
                          default_max_task_retries=0)
    assert cfg.forensics_stall_timeout_s == 2.0      # env override took
    assert cfg.forensics_dir == BUNDLE_DIR
    ray_tpu.init(num_cpus=6, config=cfg)
    yield
    ray_tpu.shutdown()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


# --- ledger lifecycle ----------------------------------------------------


def test_ledger_states_and_idempotent_exit():
    led = forensics.CollectiveLedger(size=64)
    tok = led.enter(group="g", kind="allreduce", seq=led.next_seq("g"),
                    op="sum", size=2)
    (e,) = led.snapshot()
    assert e["state"] == "in_flight" and e["seq"] == 1
    led.note(tok, sig=forensics.sig_hash(("f32", 4096)), codec="int8")
    # first terminal state wins: abort()'s stamp must not be
    # overwritten by the op's own finally-path exit
    led.exit(tok, state="aborted", err="abort(): ring declared dead")
    led.exit(tok, state="done", nbytes=123)
    (e,) = led.snapshot()
    assert e["state"] == "aborted" and "abort()" in e["err"]
    assert e["bytes"] == 0 and e["sig"] and e["codec"] == "int8"
    with pytest.raises(ValueError):
        led.exit(tok, state="in_flight")


def test_ledger_size_bound_and_enabled_knob():
    # Config.forensics_ledger_size bounds the ring; the module-level
    # ledger() reads it at first touch
    get_config().forensics_ledger_size = 16
    try:
        led = forensics.ledger()
        for i in range(40):
            led.exit(led.enter(group="g", kind="allreduce",
                               seq=led.next_seq("g")))
        assert len(led.snapshot()) == 16
        assert led.max_seq()["g"] == 40        # counters outlive eviction
    finally:
        get_config().forensics_ledger_size = 256
        forensics.reset()
    # Config.forensics_ledger is the master switch (the bench off arm)
    get_config().forensics_ledger = False
    try:
        assert not forensics.enabled()
        forensics.record_enqueued(group="g", kind="allreduce")
        assert forensics.poll_summary() is None
    finally:
        get_config().forensics_ledger = True
    assert forensics.enabled()


def test_ring_rounds_feed_the_ledger_and_abort_stamps_terminal():
    from ray_tpu.dag.channel import ShmRingChannel
    from ray_tpu.dag.ring import RingPeerDead, RingReducer

    chans = [ShmRingChannel(create=True, nslots=4, slot_bytes=1 << 20)
             for _ in range(2)]
    reds = [RingReducer(chans[r], chans[(r - 1) % 2], rank=r, size=2,
                        timeout_s=5.0, group="fxg") for r in range(2)]
    try:
        vals = [np.full(2048, float(r + 1), np.float32) for r in range(2)]
        with ThreadPoolExecutor(2) as ex:
            outs = list(ex.map(
                lambda red: red.reduce(vals[red.rank], op="sum"), reds))
        assert all(abs(o[0] - 3.0) < 1e-6 for o in outs)
        ents = [e for e in forensics.ledger().snapshot()
                if e["group"] == "fxg"]
        assert len(ents) == 2                   # one row per thread-rank
        for e in ents:
            assert e["kind"] == "allreduce" and e["state"] == "done"
            assert e["op"] == "sum" and e["size"] == 2
            assert e["bytes"] > 0 and e["t_exit"] >= e["t_enter"]
            assert e["sig"]              # header relay noted the layout

        # a blocked round abort()ed from another thread stamps the
        # in-flight row terminal 'aborted' IMMEDIATELY — a post-abort
        # audit must never see a phantom in-flight collective
        def stuck():
            with pytest.raises(RingPeerDead):
                reds[0].reduce(vals[0], op="sum")    # peer never joins

        t = threading.Thread(target=stuck)
        t.start()
        deadline = time.monotonic() + 4.0
        while time.monotonic() < deadline:
            inflight = [e for e in forensics.ledger().snapshot()
                        if e["group"] == "fxg"
                        and e["state"] == "in_flight"]
            if inflight:
                break
            time.sleep(0.01)
        assert inflight, "round never opened an in_flight row"
        reds[0].abort()
        aborted = [e for e in forensics.ledger().snapshot()
                   if e["group"] == "fxg" and e["state"] == "aborted"]
        assert aborted and "abort()" in aborted[0]["err"]
        t.join(timeout=10)
        assert not [e for e in forensics.ledger().snapshot()
                    if e["group"] == "fxg" and e["state"] == "in_flight"]
    finally:
        for c in chans:
            c.close()
            c.unlink()


# --- the cross-rank audit (synthetic per-rank snapshots) ------------------


def _snap(rank, entries, now=1000.0):
    max_seq = {}
    for e in entries:
        e.setdefault("op", None)
        e.setdefault("codec", None)
        e.setdefault("sig", "")
        e.setdefault("t_enter", now - 100.0)
        max_seq[e["group"]] = max(max_seq.get(e["group"], 0), e["seq"])
    return {"rank": rank, "now": now, "entries": entries,
            "max_seq": max_seq}


def test_audit_names_desync_minority_culprit():
    mk = lambda codec: {"group": "zero/g7", "seq": 141,
                        "kind": "allreduce", "state": "done",
                        "codec": codec}
    findings = forensics.audit({
        0: _snap(0, [mk("int4")]),
        1: _snap(1, [mk("fp32")]),
        2: _snap(2, [mk("fp32")]),
    })
    (f,) = findings
    assert f["kind"] == "collective_desync" and f["culprits"] == [0]
    assert ("seq 141 options-signature mismatch on group zero/g7: "
            "rank 0 int4 vs rank 1 fp32") == f["detail"]


def test_audit_names_stall_never_entered_rank():
    mk = lambda st: {"group": "zero/g7", "seq": 141, "kind": "allreduce",
                     "state": st}
    findings = forensics.audit({
        0: _snap(0, [mk("in_flight")]),
        1: _snap(1, [mk("in_flight")]),
        3: _snap(3, [{"group": "zero/g7", "seq": 140,
                      "kind": "allreduce", "state": "done"}]),
    }, stall_timeout_s=60.0)
    (f,) = findings
    assert f["kind"] == "collective_stall" and f["culprits"] == [3]
    assert f["detail"].startswith(
        "rank 3 never entered seq 141 of group zero/g7 (allreduce)")
    assert "blocked in it for >= 60s" in f["detail"]


def test_audit_stuck_vs_finished_and_enqueued_rows_skipped():
    # every rank ENTERED seq 5 but rank 1 is stuck while rank 0
    # finished -> the stuck side is the culprit
    findings = forensics.audit({
        0: _snap(0, [{"group": "g", "seq": 5, "kind": "allgather",
                      "state": "done"}]),
        1: _snap(1, [{"group": "g", "seq": 5, "kind": "allgather",
                      "state": "in_flight"}]),
    }, stall_timeout_s=10.0)
    (f,) = findings
    assert f["kind"] == "collective_stall" and f["culprits"] == [1]
    assert "rank 1 stuck in seq 5" in f["detail"]
    # young in-flight rows and train-plane 'enqueued' intent rows are
    # not findings
    assert forensics.audit({
        0: _snap(0, [{"group": "g", "seq": 1, "kind": "allreduce",
                      "state": "in_flight", "t_enter": 999.5}]),
        1: _snap(1, [{"group": "q:train", "seq": 1, "kind": "allreduce",
                      "state": "enqueued"}]),
    }, stall_timeout_s=60.0) == []


# --- pre-flight desync guard (Config.forensics_verify_level) --------------


class _FakeCtx:
    def __init__(self, rank, world, group_id="fxverify-0001", step=0):
        self.rank, self.world = rank, world
        self.group_id, self.collective_step = group_id, step

    def get_world_rank(self):
        return self.rank

    def get_world_size(self):
        return self.world


def _verify_level(level):
    get_config().forensics_verify_level = level


def test_preflight_verify_level_validation():
    _verify_level("bogus")
    try:
        with pytest.raises(ValueError, match="forensics_verify_level"):
            train.collective.preflight_verify(_FakeCtx(0, 2), "x")
    finally:
        _verify_level("off")
    # off is a no-op — no cluster, no rendezvous, no error
    train.collective.preflight_verify(_FakeCtx(0, 2), "x")


def test_preflight_agreement_desync_and_stall(cluster):
    from ray_tpu.train.collective import preflight_verify
    _verify_level("round")
    try:
        gid = f"fxv-{os.getpid()}"
        # agreement: both ranks post the SAME descriptor -> no raise
        with ThreadPoolExecutor(2) as ex:
            list(ex.map(
                lambda r: preflight_verify(
                    _FakeCtx(r, 2, group_id=gid), "allreduce:codec=int8",
                    timeout_s=10.0),
                range(2)))
        # desync: rank 1 is about to issue DIFFERENT wire options ->
        # both sides get the typed diagnosis in seconds, not a hang
        descs = {0: "allreduce:codec=int8", 1: "allreduce:codec=fp32"}
        errs = {}

        def go(r):
            ctx = _FakeCtx(r, 2, group_id=gid)
            ctx._fx_verify_seq = 1      # agreement round above was seq 0
            try:
                preflight_verify(ctx, descs[r], timeout_s=10.0)
            except Exception as e:      # noqa: BLE001
                errs[r] = e

        with ThreadPoolExecutor(2) as ex:
            list(ex.map(go, range(2)))
        assert set(errs) == {0, 1}
        for e in errs.values():
            assert isinstance(e, forensics.CollectiveDesyncError)
            assert "options-signature mismatch" in str(e)
            assert "rank 0 allreduce:codec=int8" in str(e)
            assert "rank 1 allreduce:codec=fp32" in str(e)
            assert e.culprits == [0, 1]        # even split: name both
        # stall: rank 1 never arrives -> typed error naming it, within
        # the deadline instead of the ring's 600s timeout
        ctx = _FakeCtx(0, 2, group_id=gid)
        ctx._fx_verify_seq = 2
        with pytest.raises(forensics.CollectiveStallError) as ei:
            preflight_verify(ctx, "allreduce:codec=int8", timeout_s=1.0)
        assert ei.value.culprits == [1]
        assert "rank 1 never entered" in str(ei.value)
        desync = [e for e in events.dump()
                  if e.get("cat") == "forensics"
                  and e.get("name") == "collective_desync"]
        assert desync and desync[0]["culprits"] == [0, 1]
    finally:
        _verify_level("off")


# --- postmortem bundles ---------------------------------------------------


def test_local_dump_and_bundle_roundtrip(tmp_path):
    forensics.set_rank(7)
    forensics.set_meta(group_id="bundletest")
    led = forensics.ledger()
    led.enter(group="g", kind="allreduce", seq=led.next_seq("g"))
    forensics.register_state_provider("t_engine", lambda: {"slots": 3})
    try:
        d = forensics.local_dump()
    finally:
        forensics.unregister_state_provider("t_engine")
    assert d["rank"] == 7 and d["meta"]["group_id"] == "bundletest"
    assert d["ledger"]["entries"][0]["state"] == "in_flight"
    assert d["state"]["t_engine"] == {"slots": 3}
    assert any("MainThread" in str(s) for s in d["stacks"])
    # Config.forensics_dir names the bundle dir; step-tagged names are
    # the runbook's postmortem-<step>.json
    path = forensics.write_bundle({"trigger": "test", "ranks": {7: d}},
                                  step=41, directory=str(tmp_path))
    assert path.endswith("postmortem-41.json")
    with open(path) as f:
        doc = json.load(f)
    assert doc["step"] == 41 and doc["trigger"] == "test"
    assert doc["ranks"]["7"]["ledger"]["entries"][0]["group"] == "g"


# --- the controller stall watchdog (real multi-process workers) -----------


def test_watchdog_names_sleeping_rank_and_writes_bundle(cluster):
    """Rank 1 parks for 8s between collectives while rank 0 enters the
    next round and blocks. The controller's poll-side watchdog
    (forensics_stall_timeout_s=2.0 here) must pull every rank's
    ledger, name rank 1 as the culprit that never entered the round,
    emit the typed collective_stall event + forensics_stall_rank
    sentinel, and write a parseable postmortem bundle — all while the
    job itself recovers and finishes clean."""

    def train_fn():
        ctx = train.get_context()
        r = ctx.get_world_rank()
        grads = {"w": np.full(1024, float(r + 1), np.float32)}
        train.allreduce_gradients(grads, op="mean")   # both ranks enter
        if r == 1:
            time.sleep(8.0)        # parked BEFORE the next collective
        out = train.allreduce_gradients(grads, op="mean")
        train.report({"rank": r, "w0": float(out["w"][0])})

    before = set(glob.glob(os.path.join(BUNDLE_DIR, "postmortem-*.json")))
    t = train.JaxTrainer(train_fn,
                         scaling_config=ScalingConfig(num_workers=2))
    res = t.fit()
    assert res.error is None and res.metrics["w0"] == 1.5

    # The controller actor lives in its own process, so its event
    # buffer and stall-rank gauge aren't readable from here — but the
    # bundle it wrote is, and the bundle CARRIES its recent events.
    new = sorted(set(glob.glob(
        os.path.join(BUNDLE_DIR, "postmortem-*.json"))) - before)
    assert new, "watchdog never fired / wrote no bundle"
    with open(new[0]) as f:
        doc = json.load(f)
    assert doc["trigger"] == "stall_watchdog"
    stall = [f for f in doc["findings"]
             if f["kind"] == "collective_stall"]
    assert stall and stall[0]["culprits"] == [1]
    assert "rank 1 never entered" in stall[0]["detail"]
    ranks = {int(k): v for k, v in doc["ranks"].items()}
    assert set(ranks) == {0, 1}
    for d in ranks.values():         # every rank contributed the full dump
        assert d["ledger"]["entries"] and d["stacks"]
    assert any(e.get("cat") == "forensics"
               and e.get("name") == "collective_stall"
               for e in doc["events"])
    # one bundle per episode — an 8s hang polled 5x a second must not
    # write 40 bundles
    assert len(new) == 1
