"""Goodput ledger: the sum-to-wall identity (interval-stamped and
pre-aggregated paths, including the clock-skew scale-down), the
``goodput_level="off"`` zero-cost discipline, counter monotonicity
through the time-series rollup, online straggler detection, the
timeline/state anatomy rows, the bubble-rate health sentinel, and
drift pinning of the GOODPUT_BENCH-seeded baseline. (Late-alphabet
name keeps the tier-1 cutoff stable.)

Knob coverage: ``goodput_level`` (RAY_TPU_GOODPUT_LEVEL),
``goodput_straggler_z``, ``goodput_straggler_window_steps``.
"""

import json
import os
import time

import pytest

from ray_tpu.config import Config
from ray_tpu.util import events
from ray_tpu.util import goodput
from ray_tpu.util import health as H
from ray_tpu.util import state
from ray_tpu.util.timeseries import TimeSeriesStore

_ROOT = os.path.join(os.path.dirname(__file__), os.pardir)


class FakeClock:
    def __init__(self, t0=1000.0):
        self.t = t0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


@pytest.fixture(autouse=True)
def _fresh_ledger():
    goodput.reset()
    goodput.set_level("step")
    goodput.set_rank(-1)
    yield
    goodput.reset()


def _seconds_total():
    m = goodput.goodput_metrics()["seconds"]
    return sum(m._values.values())


# --- the sum-to-wall identity -----------------------------------------------


def test_interval_path_identity_and_carveout():
    """Stamped intervals + add() carve-outs partition the step wall
    exactly: an add() inside an open interval is carved OUT of the
    enclosing category, synthetic add()s land verbatim, and idle
    absorbs the residual."""
    goodput.step_begin(7, rank=3)
    with goodput.interval("compute"):
        time.sleep(0.005)
        goodput.add("comm_exposed", 0.001)      # carved out of compute
    goodput.add("ckpt_stall", 0.0005)           # outside any interval
    time.sleep(0.002)                           # unclaimed -> idle
    goodput.step_end()
    rows = goodput.recent_rows()
    assert len(rows) == 1
    row = rows[0]
    assert row["step"] == 7 and row["rank"] == 3
    total = row["idle"] + sum(row[c] for c in goodput.STAMPED)
    assert total == pytest.approx(row["wall_s"], abs=1e-9)
    # the adds were not scaled (stamped < wall here), and the carve
    # kept the interval's own time exclusive of the inner add
    assert row["comm_exposed"] == pytest.approx(0.001, abs=1e-9)
    assert row["ckpt_stall"] == pytest.approx(0.0005, abs=1e-9)
    assert 0.003 < row["compute"] < row["wall_s"]
    assert row["idle"] > 0.0


def test_nested_intervals_never_double_count():
    """An inner interval's whole span is carved from its parent, so
    compute + comm_exposed <= wall even when one wraps the other."""
    goodput.step_begin(1, rank=0)
    with goodput.interval("compute"):
        time.sleep(0.002)
        with goodput.interval("comm_exposed"):
            time.sleep(0.002)
        with goodput.interval("compute"):       # same-category re-entry
            time.sleep(0.001)
    goodput.step_end()
    row = goodput.recent_rows()[0]
    assert row["compute"] + row["comm_exposed"] <= row["wall_s"] + 1e-9
    assert row["compute"] > 0.0 and row["comm_exposed"] >= 0.002 - 1e-4
    total = row["idle"] + sum(row[c] for c in goodput.STAMPED)
    assert total == pytest.approx(row["wall_s"], abs=1e-9)


def test_record_step_identity_and_scale_down():
    # residual path: unclaimed wall becomes idle
    goodput.record_step(5, 0.1, rank=2, compute=0.06, bubble=0.02)
    row = goodput.recent_rows()[-1]
    assert row["idle"] == pytest.approx(0.02, abs=1e-12)
    assert row["idle"] + sum(row[c] for c in goodput.STAMPED) == \
        pytest.approx(row["wall_s"], abs=1e-12)
    # clock-skew path: stamped > wall scales down (never negative idle),
    # preserving proportions and the exact identity
    goodput.record_step(6, 0.05, rank=2, compute=0.06,
                        comm_exposed=0.06)
    row = goodput.recent_rows()[-1]
    assert row["idle"] == 0.0
    assert row["compute"] == pytest.approx(row["comm_exposed"])
    assert sum(row[c] for c in goodput.STAMPED) == \
        pytest.approx(0.05, abs=1e-12)
    # negative/unknown categories are dropped, not booked
    goodput.record_step(8, 0.01, rank=2, compute=-1.0, nonsense=0.5)
    row = goodput.recent_rows()[-1]
    assert row["compute"] == 0.0 and row["idle"] == \
        pytest.approx(0.01, abs=1e-12)


def test_reentrant_step_window_is_depth_counted():
    """A nested trace_step (e.g. a user fn that itself calls the
    trainer) must not close the outer window early or emit two rows."""
    goodput.step_begin(1, rank=0)
    goodput.step_begin(1)
    goodput.add("compute", 0.001)
    goodput.step_end()                  # closes the nested entry only
    assert goodput.recent_rows() == []
    goodput.step_end()
    assert len(goodput.recent_rows()) == 1


# --- the off discipline ------------------------------------------------------


def test_off_level_records_nothing():
    """goodput_level="off" (RAY_TPU_GOODPUT_LEVEL=off) is the
    collective_trace_level discipline: every call early-returns — no
    rows, no counters, no events, and interval() hands back the shared
    no-op (no per-call allocation)."""
    goodput.set_level("off")
    assert not goodput.enabled()
    before = _seconds_total()
    n_events = sum(1 for e in events.dump()
                   if e.get("cat") == "goodput")
    goodput.step_begin(1, rank=0)
    with goodput.interval("compute"):
        pass
    goodput.add("comm_exposed", 1.0)
    goodput.step_end()
    goodput.record_step(2, 1.0, rank=0, compute=0.5)
    assert goodput.recent_rows() == []
    assert goodput.anatomy() is None
    assert _seconds_total() == before
    assert sum(1 for e in events.dump()
               if e.get("cat") == "goodput") == n_events
    assert goodput.interval("compute") is goodput.interval("bubble")


def test_level_knob_resolves_from_config(monkeypatch):
    """The lazily-cached level re-resolves from Config after reset():
    the goodput_level knob is the production switch."""
    assert Config().goodput_level == "step"
    assert Config(goodput_level="off").goodput_level == "off"
    monkeypatch.setenv("RAY_TPU_GOODPUT_LEVEL", "off")
    import ray_tpu.config as C
    cfg = C.Config.from_env()
    assert cfg.goodput_level == "off"


def test_straggler_knob_defaults():
    c = Config()
    assert c.goodput_straggler_z == 6.0
    assert c.goodput_straggler_window_steps == 32


# --- metrics: counters, rollup monotonicity, MFU ----------------------------


def test_counters_monotone_through_rollup():
    """goodput_seconds_total flows through the head's time-series
    store like any pushed counter: per-window increments are never
    negative and sum to the cumulative delta."""
    clk = FakeClock(t0=50_000.0)
    s = TimeSeriesStore(clock=clk, window_s=10.0, retention_s=900.0)
    key = (("category", "compute"), ("rank", "0"))
    m = goodput.goodput_metrics()["seconds"]
    first = m._values.get(key, 0.0)
    s.ingest_counter("goodput_seconds_total",
                     dict(key), first, source="w0")
    for i in range(8):
        goodput.record_step(i, 0.05, rank=0, compute=0.03)
        clk.advance(10.0)
        s.ingest_counter("goodput_seconds_total", dict(key),
                         m._values.get(key, 0.0), source="w0")
    last = m._values.get(key, 0.0)
    assert last == pytest.approx(first + 8 * 0.03, abs=1e-9)
    q = s.query("goodput_seconds_total", since_s=300.0)
    assert q["kind"] == "counter" and q["points"]
    assert all(p["inc"] >= 0.0 and p["rate"] >= 0.0
               for p in q["points"])
    assert sum(p["inc"] for p in q["points"]) == \
        pytest.approx(last - first, abs=1e-9)
    # every closed row also ticks the step counter
    steps = goodput.goodput_metrics()["steps"]
    assert steps._values.get((("rank", "0"),), 0.0) >= 8


def test_mfu_gauge_from_registered_flops():
    """train_mfu = flops_per_step / wall / peak: 1e12 FLOPs in 1s on a
    100-TFLOP part is 1% MFU. Explicit peak wins; device_kind resolves
    through accelerators.peak_tflops."""
    goodput.set_model_flops(1e12, peak_tflops=100.0)
    goodput.record_step(1, 1.0, rank=4, compute=0.9)
    g = goodput.goodput_metrics()["mfu"]
    assert g._values[(("rank", "4"),)] == pytest.approx(0.01)
    from ray_tpu.util.accelerators import peak_tflops
    assert peak_tflops("TPU v5e") == 197.0
    assert peak_tflops("TPU v5p") == 459.0
    # unknown kinds warn (once) and fall back rather than crash
    assert peak_tflops("TPU v99") == 197.0


# --- straggler detection -----------------------------------------------------


def _an(rank, compute, comm, steps=16):
    return {"rank": rank, "steps": steps, "wall_p50": 0.1,
            "p50": {"compute": compute, "comm_exposed": comm,
                    "bubble": 0.0, "ckpt_stall": 0.0, "compile": 0.0,
                    "idle": 0.0}}


def test_straggler_detector_names_injected_slow_rank():
    det = goodput.StragglerDetector(z_threshold=6.0, min_steps=8)
    for r in range(4):
        if r == 2:      # the slow rank computes longer, waits less
            det.observe(r, _an(r, compute=0.050, comm=0.001))
        else:           # healthy ranks absorb the wait
            det.observe(r, _an(r, compute=0.010, comm=0.041))
    v = det.check()
    assert v["rank"] == 2
    assert v["z"] >= 6.0 and v["gap_s"] >= 0.005


def test_straggler_detector_quiet_on_uniform_ranks():
    det = goodput.StragglerDetector(z_threshold=6.0, min_steps=8)
    for r in range(4):
        det.observe(r, _an(r, compute=0.010 + 0.0001 * r, comm=0.040))
    assert det.check()["rank"] == -1
    # too few ranks / too few steps: never flags
    det2 = goodput.StragglerDetector(min_steps=8)
    det2.observe(0, _an(0, 0.5, 0.0))
    det2.observe(1, _an(1, 0.01, 0.04))
    assert det2.check()["rank"] == -1
    det2.observe(2, _an(2, 0.01, 0.04, steps=2))    # below min_steps
    assert det2.check()["rank"] == -1


def test_anatomy_window_feeds_detector_end_to_end():
    """Ledger rows -> anatomy() p50 summary -> detector: the shape the
    worker poll ships and the controller consumes."""
    for i in range(12):
        goodput.record_step(i, 0.1, rank=5, compute=0.08,
                            comm_exposed=0.001)
    an = goodput.anatomy()
    assert an["rank"] == 5 and an["steps"] == 12
    assert an["p50"]["compute"] == pytest.approx(0.08)
    assert an["wall_p50"] == pytest.approx(0.1)
    det = goodput.StragglerDetector(z_threshold=6.0, min_steps=8)
    det.observe(5, an)
    det.observe(0, _an(0, compute=0.010, comm=0.060))
    det.observe(1, _an(1, compute=0.010, comm=0.060))
    assert det.check()["rank"] == 5


def test_window_respects_straggler_window_knob():
    """The rolling anatomy window is goodput_straggler_window_steps
    deep — old steps age out instead of growing without bound."""
    for i in range(50):
        goodput.record_step(i, 0.01, rank=0, compute=0.005)
    rows = goodput.recent_rows()
    assert len(rows) == Config().goodput_straggler_window_steps
    assert rows[0]["step"] == 50 - len(rows)


# --- timeline events / state rows -------------------------------------------


def test_step_events_and_state_anatomy_rows():
    goodput.set_model_flops(1e12, peak_tflops=100.0)
    for i in range(4):
        goodput.record_step(i, 0.1, rank=1, compute=0.06,
                            comm_exposed=0.02, bubble=0.01)
    evts = [e for e in events.dump() if e.get("cat") == "goodput"
            and e.get("name") == "step" and e.get("rank") == 1]
    assert len(evts) >= 4
    e = evts[-1]
    assert e["wall_s"] == pytest.approx(0.1, abs=1e-6)
    booked = (e["idle_s"]
              + sum(e[f"{c}_s"] for c in goodput.STAMPED))
    assert booked == pytest.approx(e["wall_s"], abs=1e-5)
    rows = state.goodput_from_events(evts)
    assert len(rows) == 1 and rows[0]["rank"] == 1
    assert rows[0]["steps"] >= 4
    assert rows[0]["mean_compute_s"] == pytest.approx(0.06, abs=1e-6)
    assert rows[0]["goodput_fraction"] == pytest.approx(0.6, abs=1e-4)
    # 1e12 FLOPs / 0.1 s wall against 100 TFLOPs peak -> 10% MFU
    assert rows[0]["mfu"] == pytest.approx(0.1, abs=1e-4)


# --- health plane ------------------------------------------------------------


def test_bubble_sentinel_fires_through_health_engine():
    """The GOODPUT_BENCH-seeded sentinel watches the bubble counter's
    rate: exposed pipeline idle seconds per wall second beyond
    baseline*tolerance is a firing regression."""
    clk = FakeClock(t0=500_000.0)
    s = TimeSeriesStore(clock=clk, window_s=10.0, retention_s=900.0)
    baseline = {"sentinels": [{
        "name": "goodput_bubble_rate",
        "metric": "goodput_seconds_total",
        "labels": {"category": "bubble"}, "stat": "rate",
        "window_s": 120, "baseline": 0.2, "tolerance": 3.0,
        "source": "unit"}]}
    cfg = Config(slo_default_objectives=False)
    eng = H.HealthEngine(s, cfg, clock=clk, baseline=baseline)
    labels = {"category": "bubble", "rank": "0"}
    cum = 0.0
    for _ in range(12):                 # healthy: ~0.1 s/s of bubble
        clk.advance(10.0)
        cum += 1.0
        s.ingest_counter("goodput_seconds_total", labels, cum,
                         source="w0")
    snap = eng.evaluate()
    row = snap["sentinels"][0]
    assert row["live"] is not None and not row["breached"]
    for _ in range(12):                 # regressed: ~0.9 s/s
        clk.advance(10.0)
        cum += 9.0
        s.ingest_counter("goodput_seconds_total", labels, cum,
                         source="w0")
    snap = eng.evaluate()
    row = snap["sentinels"][0]
    assert row["breached"] and row["ratio"] > 3.0
    assert ("goodput_bubble_rate", "sentinel", "firing") in \
        snap["transitions"]


def test_straggler_gauge_derives_health_objective():
    clk = FakeClock(t0=1000.0)
    s = TimeSeriesStore(clock=clk, window_s=10.0, retention_s=900.0)
    s.ingest_gauge("goodput_straggler_rank", None, -1.0)
    eng = H.HealthEngine(
        s, Config(slo_default_objectives=True), clock=clk)
    names = {o.name for o in eng.active_objectives()}
    assert "goodput_straggler" in names


def test_straggler_gauge_query_exposes_last_sample():
    # a rank-id gauge is meaningless averaged: a window that saw both
    # -1 (healthy polls) and 2 (straggler fired) must still report the
    # NEWEST sample as "last" (the CLI/dashboard read that, not the
    # window-mean "value")
    clk = FakeClock(t0=1000.0)
    s = TimeSeriesStore(clock=clk, window_s=10.0, retention_s=900.0)
    for v in (-1.0, -1.0, 2.0):
        s.ingest_gauge("goodput_straggler_rank", None, v)
        clk.advance(0.5)
    q = s.query("goodput_straggler_rank", since_s=60.0)
    pt = q["points"][-1]
    assert pt["last"] == 2.0
    assert pt["value"] == pytest.approx(0.0)   # the useless mean
    assert pt["min"] == -1.0 and pt["max"] == 2.0


# --- CLI surface -------------------------------------------------------------


def test_cli_goodput_renders_anatomy_and_mfu(monkeypatch, capsys):
    from ray_tpu import scripts as S
    goodput.set_model_flops(1e12, peak_tflops=100.0)
    for i in range(6):
        goodput.record_step(i, 0.1, rank=20, compute=0.07,
                            comm_exposed=0.02)
        goodput.record_step(i, 0.1, rank=21, compute=0.05,
                            bubble=0.03)
    # the events ring is process-global: keep only this test's ranks
    evts = [e for e in events.dump() if e.get("cat") == "goodput"
            and e.get("rank") in (20, 21)]
    series = {
        "train_mfu": {"name": "train_mfu", "kind": "gauge",
                      "window_s": 10.0, "series": 1,
                      "points": [{"t": 0.0, "value": 0.08},
                                 {"t": 10.0, "value": 0.1}]},
        "goodput_straggler_rank": {
            "name": "goodput_straggler_rank", "kind": "gauge",
            "window_s": 10.0, "series": 1,
            "points": [{"t": 10.0, "value": 1.0}]},
    }

    def fake_call(addr, method, timeout=10.0, **kw):
        if method == "collect_timeline":
            return {"events": evts}
        return series[kw["name"]]

    monkeypatch.setattr(S, "_call_head", fake_call)
    monkeypatch.setattr(S, "_resolve_address", lambda a: "h:1")
    assert S.main(["goodput"]) == 0
    out = capsys.readouterr().out
    assert "anatomy" in out and "#" in out          # stacked bar
    assert "train_mfu" in out and "10.0%" in out
    assert "STRAGGLER: rank 1" in out
    assert S.main(["goodput", "--json"]) == 0
    j = json.loads(capsys.readouterr().out)
    assert {r["rank"] for r in j["rows"]} == {20, 21}
    assert j["straggler_rank"] == 1
    assert j["mfu_trend"] == [0.08, 0.1]


# --- bench drift pinning -----------------------------------------------------


def test_goodput_bench_seeds_health_baseline():
    """The committed sentinel baseline must recompute from
    GOODPUT_BENCH.json — regenerating the bench without reseeding is a
    loud failure (same contract as test_zz_health's drift test)."""
    with open(os.path.join(_ROOT, "HEALTH_BASELINE.json")) as f:
        base = json.load(f)
    sent = {x["name"]: x for x in base["sentinels"]}
    assert "goodput_bubble_rate" in sent
    with open(os.path.join(_ROOT, "GOODPUT_BENCH.json")) as f:
        gb = json.load(f)
    assert sent["goodput_bubble_rate"]["baseline"] == pytest.approx(
        gb["bubble_fraction_measured"], rel=1e-4)
    assert sent["goodput_bubble_rate"]["labels"] == {
        "category": "bubble"}
    # the bench's own acceptance: default-level stamping is noise on a
    # realistic step, and the ledger's measured bubble tracks the
    # analytic (S-1)/(M+S-1) bound for the 2-stage M=4 run
    assert gb["on_vs_off_step"] < 1.25
    assert 0.8 < gb["bubble_vs_analytic"] < 1.6
    assert gb["overhead"]["micro"]["rows_per_rep_off"] == 0
    assert gb["overhead"]["micro"]["rows_per_rep_on"] > 0
