"""Cluster health plane: time-series store math, SLO burn-rate
evaluation, regression sentinels, lint/knob coverage, worker final
metrics flush, CLI/endpoint surfaces — plus a slow live-cluster e2e
where an injected TTFT degradation (chaos delay at the replica) fires
the fast-burn page-tier alert with a resolvable exemplar trace id and
recovery clears it. (Late-alphabet name keeps the tier-1 cutoff
stable.)

Every window/burn test drives an injectable clock — no wall-clock
sleeps in the fast tier.
"""

import asyncio
import http.client
import importlib.util
import json
import os
import threading
import time

import pytest

from ray_tpu.config import Config
from ray_tpu.util import events
from ray_tpu.util import health as H
from ray_tpu.util import metrics as M
from ray_tpu.util.timeseries import (TimeSeriesStore, _bucket_quantile,
                                     _labels_key)


class FakeClock:
    def __init__(self, t0=1000.0):
        self.t = t0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _store(clock, **kw):
    kw.setdefault("window_s", 10.0)
    kw.setdefault("retention_s", 900.0)
    return TimeSeriesStore(clock=clock, **kw)


# --- time-series store math -------------------------------------------------


def test_counter_gauge_ingest_and_query():
    clk = FakeClock()
    s = _store(clk)
    s.ingest_counter("reqs_total", {"dep": "a"}, 0.0, source="w1")
    for i in range(6):
        clk.advance(10.0)
        s.ingest_counter("reqs_total", {"dep": "a"}, (i + 1) * 5.0,
                         source="w1")
        s.ingest_gauge("depth", {"dep": "a"}, float(i))
    q = s.query("reqs_total", since_s=120.0)
    assert q["kind"] == "counter"
    # 5 increments per 10s window -> 0.5/s in each full window
    assert all(abs(p["rate"] - 0.5) < 1e-9 for p in q["points"])
    g = s.query("depth", since_s=120.0)
    assert g["kind"] == "gauge"
    assert [p["value"] for p in g["points"]] == [0, 1, 2, 3, 4, 5]
    assert g["points"][-1]["min"] == 5 and g["points"][-1]["max"] == 5
    # label-subset selection: an unmatched selector returns nothing
    assert s.query("reqs_total", 120.0, {"dep": "b"})["series"] == 0
    assert s.query("reqs_total", 120.0, {"dep": "a"})["series"] == 1


def test_counter_rollup_preserves_monotonic_increments():
    """The downsample contract: summed 1-min rollup increments equal
    summed raw increments over the same span, and a counter RESET
    (worker restart) contributes the post-reset value — never a
    negative increment at any resolution."""
    clk = FakeClock(t0=10_000.0)
    s = _store(clk)
    total = 0.0
    cum = 0.0
    for i in range(30):          # 5 minutes of 10s pushes
        clk.advance(10.0)
        if i == 17:              # restart: cumulative drops to 3
            cum = 3.0
        else:
            cum += 7.0
        s.ingest_counter("work_total", None, cum, source="w1")
        # the store's FIRST sight (i=0) is a baseline, not an
        # increment — a long-lived source joining a fresh store must
        # not dump its lifetime count into one window
        if i != 0:
            total += 3.0 if i == 17 else 7.0
    raw = s.window("work_total", 300.0)
    assert raw["kind"] == "counter"
    assert abs(raw["inc"] - total) < 1e-9
    # every stored window at every resolution is non-negative
    key = ("work_total", _labels_key(None))
    series = s._series[key]
    for ring in series.rings:
        for b in ring:
            assert b.get("inc", 0.0) >= 0.0
    # rollup sum == raw sum over the full span (same deltas, coarser
    # alignment — reconstructed cumulative stays monotone everywhere)
    raw_sum = sum(b.get("inc", 0.0) for b in series.rings[0])
    mid_sum = sum(b.get("inc", 0.0) for b in series.rings[1])
    assert abs(raw_sum - mid_sum) < 1e-9
    assert abs(raw_sum - total) < 1e-9


def test_histogram_mergeability_quantile_over_window():
    """quantile(window) == quantile(merged buckets): identical at raw
    and rollup resolutions because both store the same per-window
    bucket DELTAS (prometheus cumulative-le unstacked at ingest)."""
    clk = FakeClock(t0=50_000.0)
    s = _store(clk)
    bounds = (0.1, 0.25, 0.5, 1.0)
    cum = [0, 0, 0, 0, 0]
    csum = 0.0
    for i in range(24):          # 4 minutes of pushes
        clk.advance(10.0)
        # 8 fast (le .1), 2 slow (le 1.0) per push
        cum[0] += 8
        cum[3] += 2
        csum += 8 * 0.05 + 2 * 0.8
        s.ingest_hist("lat_s", {"dep": "x"}, bounds, list(cum), csum,
                      source="w1")
    # 24 pushes, the first is a baseline -> 23 increments recorded
    w = s.window("lat_s", 240.0, {"dep": "x"})
    assert w["count"] == 230
    assert w["counts"][0] == 184 and w["counts"][3] == 46
    p50 = s.quantile("lat_s", 0.5, 240.0, {"dep": "x"})
    assert p50 is not None and p50 <= 0.1
    p95 = s.quantile("lat_s", 0.95, 240.0, {"dep": "x"})
    assert 0.5 < p95 <= 1.0
    # same answer from the 1-min rollup ring (mergeable deltas)
    key = ("lat_s", _labels_key({"dep": "x"}))
    series = s._series[key]
    merged = [0.0] * 5
    for b in series.rings[1]:
        for i, c in enumerate(b.get("counts") or []):
            merged[i] += c
    assert merged == w["counts"]
    assert abs(_bucket_quantile(bounds, merged, 0.95) - p95) < 1e-9


def test_bucket_quantile_interpolation():
    bounds = (1.0, 2.0, 4.0)
    counts = [10, 10, 0, 0]
    assert _bucket_quantile(bounds, counts, 0.5) == pytest.approx(1.0)
    assert _bucket_quantile(bounds, counts, 0.75) == pytest.approx(1.5)
    # overflow bucket clamps to the largest boundary
    assert _bucket_quantile(bounds, [0, 0, 0, 5], 0.99) == 4.0
    assert _bucket_quantile((), [], 0.5) == 0.0


def test_ring_eviction_order_and_series_memory_bound():
    clk = FakeClock(t0=0.0)
    s = _store(clk, window_s=10.0, retention_s=100.0, max_series=3)
    # fill 3x the raw retention: only the newest windows survive,
    # evicted strictly oldest-first
    for i in range(30):
        clk.advance(10.0)
        s.ingest_gauge("g", None, float(i))
    ring = s._series[("g", ())].rings[0]
    ts = [b["t"] for b in ring]
    assert ts == sorted(ts)
    assert len(ring) == ring.maxlen
    assert ts[0] >= clk.t - 110.0    # oldest retained is recent
    # series bound: 4th distinct series evicts the least-recently
    # updated one
    s.ingest_gauge("a", None, 1.0)
    s.ingest_gauge("b", None, 1.0)
    clk.advance(10.0)
    s.ingest_gauge("g", None, 99.0)    # refresh g
    s.ingest_gauge("c", None, 1.0)     # 4th: evicts a or b, never g
    assert s.series_count() == 3
    assert s.dropped_series_total == 1
    assert ("g", ()) in s._series and ("c", ()) in s._series


def test_ingest_text_counters_gauges_hists_and_exemplars():
    clk = FakeClock(t0=5_000.0)
    s = _store(clk)
    text = "\n".join([
        'reqs_total{node="n1",dep="a"} 10',
        'depth{node="n1"} 3',
        'lat_s_bucket{node="n1",le="0.25"} 4',
        'lat_s_bucket{node="n1",le="1"} 9 '
        '# {trace_id="cafe01"} 0.8 4999.5',
        'lat_s_bucket{node="n1",le="+Inf"} 10',
        'lat_s_sum{node="n1"} 3.5',
        'lat_s_count{node="n1"} 10',
        '# HELP ignored comment',
    ])
    s.ingest_text("w1", text)
    clk.advance(10.0)
    s.ingest_text("w1", text.replace(" 10", " 30")
                  .replace('le="0.25"} 4', 'le="0.25"} 8')
                  .replace('le="1"} 9', 'le="1"} 19'))
    w = s.window("reqs_total", 60.0)
    # first push (10) is the baseline; second (30) -> increment 20
    assert w["kind"] == "counter" and w["inc"] == 20.0
    g = s.window("depth", 60.0)
    assert g["kind"] == "gauge" and g["last"] == 3.0
    h = s.window("lat_s", 60.0)
    assert h["kind"] == "histogram"
    assert h["boundaries"] == [0.25, 1.0]
    # first push [4,5,1] is the baseline; second unstacks cumulative
    # 8/19/30 -> [8,11,11], recorded delta [4,6,10]
    assert h["counts"] == [4.0, 6.0, 10.0]
    # the exemplar rode the bucket line into the window, index 1 (le=1)
    assert 1 in h["exemplars"]
    assert h["exemplars"][1][0] == "cafe01"
    q = s.quantile("lat_s", 0.5, 60.0)
    assert 0.25 < q <= 1.0


def test_ingest_registry_roundtrip_through_rendered_text():
    """A real metrics.Histogram rendered by render_labeled parses back
    into the store (the worker-push path end to end, in-process).
    Two pushes: the first is the store's baseline, the deltas between
    them are what lands in windows."""
    clk = FakeClock(t0=9_000.0)
    s = _store(clk)
    h = M.Histogram("zz_health_rt_s", "roundtrip test",
                    boundaries=(0.1, 1.0))
    c = M.Counter("zz_health_rt_total", "roundtrip test")
    h.observe(0.02, {"dep": "a"})
    c.inc(1.0)
    s.ingest_text("w9", M.render_labeled({"node": "n9"}))  # baseline
    clk.advance(10.0)
    h.observe(0.05, {"dep": "a"})
    h.observe(0.7, {"dep": "a"}, exemplar="beef02")
    c.inc(4.0)
    s.ingest_text("w9", M.render_labeled({"node": "n9"}))
    w = s.window("zz_health_rt_s", 60.0, {"dep": "a"})
    assert w is not None and w["count"] == 2
    assert w["exemplars"] and any(
        e[0] == "beef02" for e in w["exemplars"].values())
    cw = s.window("zz_health_rt_total", 60.0)
    assert cw["inc"] == 4.0
    # local registry ingestion: same two-phase contract
    s2 = _store(clk)
    s2.ingest_registry()
    h.observe(0.3, {"dep": "a"})
    clk.advance(10.0)
    s2.ingest_registry()
    w2 = s2.window("zz_health_rt_s", 60.0, {"dep": "a"})
    assert w2 is not None and w2["count"] == 1


def test_big_counter_renders_full_precision_for_delta_math():
    """%g rendering would freeze a pushed counter at '1e+07' and the
    store's deltas (and availability burn rates) would read 0 — the
    push path must render full precision."""
    clk = FakeClock(t0=11_000.0)
    s = _store(clk)
    c = M.Counter("zz_health_big_total", "precision test")
    c.inc(10_000_000.0)
    s.ingest_text("wb", M.render_labeled({"node": "nb"}))  # baseline
    clk.advance(10.0)
    c.inc(40.0)
    text = M.render_labeled({"node": "nb"})
    assert "10000040" in text, text.splitlines()[:3]
    s.ingest_text("wb", text)
    w = s.window("zz_health_big_total", 60.0)
    assert w["inc"] == 40.0


# --- SLO engine -------------------------------------------------------------


def _cfg(**kw):
    kw.setdefault("slo_fast_windows_s", "30,120")
    kw.setdefault("slo_slow_windows_s", "120,600")
    kw.setdefault("slo_fast_burn", 10.0)
    kw.setdefault("slo_slow_burn", 2.0)
    kw.setdefault("slo_default_objectives", False)
    return Config(**kw)


def _push_lat(s, clk, dep, n_fast, n_slow, cum, bounds=(0.25, 1.0)):
    """One push of the serve handler histogram: n_fast requests at
    ~0.1s, n_slow at ~0.8s (cumulative state threaded by caller)."""
    cum["f"] += n_fast
    cum["s"] += n_slow
    cum["sum"] += n_fast * 0.1 + n_slow * 0.8
    s.ingest_hist("serve_proxy_handler_s", {"deployment": dep}, bounds,
                  [cum["f"], cum["s"], 0.0], cum["sum"], source="w1",
                  exemplars={1: ("abad1dea", 0.8, clk.t)}
                  if n_slow else None)


def test_burn_rate_multi_window_deterministic():
    """Fast-burn page alert needs BOTH fast windows over threshold:
    a short bad burst trips the 30s window but not the 120s one (no
    page); sustained badness trips both (page fires, event recorded,
    exemplar attached); recovery resolves it. Injectable clock, zero
    sleeps."""
    clk = FakeClock(t0=100_000.0)
    s = _store(clk)
    obj = H.Objective(name="lat:a", kind="latency",
                      metric="serve_proxy_handler_s",
                      labels={"deployment": "a"}, threshold_s=0.25,
                      target=0.99, deployment="a")
    eng = H.HealthEngine(s, _cfg(), clock=clk, objectives=[obj])
    cum = {"f": 0, "s": 0, "sum": 0.0}
    # 2 minutes healthy
    for _ in range(12):
        clk.advance(10.0)
        _push_lat(s, clk, "a", n_fast=10, n_slow=0, cum=cum)
    snap = eng.evaluate()
    page = snap["objectives"][0]["tiers"]["page"]
    assert page["burn_short"] == 0.0 and not page["firing"]
    # one bad 10s window: short window burns, long window diluted
    clk.advance(10.0)
    _push_lat(s, clk, "a", n_fast=0, n_slow=10, cum=cum)
    snap = eng.evaluate()
    page = snap["objectives"][0]["tiers"]["page"]
    assert page["burn_short"] >= 10.0          # 1/3 bad over 30s
    assert not page["firing"]                  # 120s window saved us
    assert not [a for a in snap["alerts"] if a["tier"] == "page"]
    # sustained: 2 more bad minutes -> both windows over threshold
    fired_at = None
    for i in range(12):
        clk.advance(10.0)
        _push_lat(s, clk, "a", n_fast=0, n_slow=10, cum=cum)
        snap = eng.evaluate()
        if snap["objectives"][0]["tiers"]["page"]["firing"]:
            fired_at = i
            break
    assert fired_at is not None, "page alert never fired"
    assert ("lat:a", "page", "firing") in snap["transitions"]
    assert snap["alerts"] and snap["alerts"][0]["tier"] == "page"
    # exemplar from the breaching bucket names a concrete trace
    assert snap["alerts"][0]["exemplar"] == "abad1dea"
    assert snap["burn_advice"]["a"]["latency_burning"]
    assert snap["burn_advice"]["a"]["tier"] == "page"
    # the transition landed in the "health" event category
    evs = [e for e in events.dump() if e.get("cat") == "health"
           and e.get("objective") == "lat:a"
           and e.get("state") == "firing"]
    assert evs and evs[-1].get("trace") == "abad1dea"
    assert evs[-1].get("tier") == "page"
    # recovery: healthy traffic until both windows drain
    resolved = False
    for _ in range(30):
        clk.advance(10.0)
        _push_lat(s, clk, "a", n_fast=10, n_slow=0, cum=cum)
        snap = eng.evaluate()
        if ("lat:a", "page", "resolved") in snap["transitions"]:
            resolved = True
            break
    assert resolved, "alert never resolved after recovery"
    # page tier is clear (the warn tier's 600s window legitimately
    # remembers the incident longer)
    assert not [a for a in snap["alerts"] if a["tier"] == "page"]
    assert any(e.get("cat") == "health" and e.get("state") == "resolved"
               and e.get("objective") == "lat:a"
               for e in events.dump())


def test_availability_burn_counts_5xx_over_total():
    clk = FakeClock(t0=200_000.0)
    s = _store(clk)
    obj = H.Objective(
        name="avail:a", kind="availability",
        metric="serve_requests_total",
        labels={"deployment": "a"}, target=0.99,
        bad_labels=[{"deployment": "a", "code": c}
                    for c in ("500", "503", "504")],
        deployment="a")
    eng = H.HealthEngine(s, _cfg(), clock=clk, objectives=[obj])
    ok = bad = 0
    for i in range(18):         # 3 minutes; 5xx storm from minute 2
        clk.advance(10.0)
        ok += 10
        s.ingest_counter("serve_requests_total",
                         {"deployment": "a", "code": "200"}, ok,
                         source="w1")
        if i >= 12:
            bad += 10
            s.ingest_counter("serve_requests_total",
                             {"deployment": "a", "code": "503"}, bad,
                             source="w1")
    snap = eng.evaluate()
    page = snap["objectives"][0]["tiers"]["page"]
    assert page["firing"], snap["objectives"][0]
    assert snap["burn_advice"]["a"]["availability_burning"]
    # and a clean deployment's objective stays quiet
    s.ingest_counter("serve_requests_total",
                     {"deployment": "b", "code": "200"}, 50,
                     source="w1")
    obj_b = H.Objective(
        name="avail:b", kind="availability",
        metric="serve_requests_total",
        labels={"deployment": "b"}, target=0.99,
        bad_labels=[{"deployment": "b", "code": "500"}],
        deployment="b")
    eng.add_objective(obj_b)
    clk.advance(10.0)
    s.ingest_counter("serve_requests_total",
                     {"deployment": "b", "code": "200"}, 90,
                     source="w1")
    snap = eng.evaluate()
    rows = {o["name"]: o for o in snap["objectives"]}
    assert not rows["avail:b"]["tiers"]["page"]["firing"]


def test_gauge_objective_sustained_straggler():
    """allreduce_straggler_rank: -1 healthy; a rank flagged over BOTH
    windows fires (burn inf); one blip does not."""
    clk = FakeClock(t0=300_000.0)
    s = _store(clk)
    obj = H.Objective(name="straggler", kind="gauge",
                      metric="allreduce_straggler_rank",
                      threshold=-0.5, direction="above")
    eng = H.HealthEngine(s, _cfg(), clock=clk, objectives=[obj])
    for _ in range(13):
        clk.advance(10.0)
        s.ingest_gauge("allreduce_straggler_rank", None, -1.0)
    clk.advance(10.0)
    s.ingest_gauge("allreduce_straggler_rank", None, 2.0)   # one blip
    snap = eng.evaluate()
    assert not snap["objectives"][0]["tiers"]["page"]["firing"]
    for _ in range(13):         # sustained: rank 2 stuck for 130s
        clk.advance(10.0)
        s.ingest_gauge("allreduce_straggler_rank", None, 2.0)
    snap = eng.evaluate()
    assert snap["objectives"][0]["tiers"]["page"]["firing"]
    assert snap["objectives"][0]["tiers"]["page"]["burn_short"] == -1.0
    # a firing gauge alert's snapshot is STRICT JSON: inf is encoded
    # as -1 everywhere (allow_nan=False raises on a raw Infinity)
    json.dumps(snap, allow_nan=False)
    assert snap["alerts"] and snap["alerts"][0]["burn_short"] == -1.0


def test_gauge_ratio_worst_device_decides():
    """One saturated device among idle ones must fire hbm_headroom:
    the ratio is per numerator series (its own divisor), worst wins —
    merging used bytes across devices would hide the hot one."""
    clk = FakeClock(t0=350_000.0)
    s = _store(clk)
    obj = H.Objective(name="hbm", kind="gauge_ratio",
                      metric="device_hbm_used_bytes",
                      divisor_metric="device_hbm_limit_bytes",
                      threshold=0.92, direction="above")
    eng = H.HealthEngine(s, _cfg(), clock=clk, objectives=[obj])
    for _ in range(14):
        clk.advance(10.0)
        for d in range(4):
            used = 9.7e9 if d == 0 else 4.0e9   # device 0 at 97%
            s.ingest_gauge("device_hbm_used_bytes",
                           {"device": f"tpu:{d}"}, used)
            s.ingest_gauge("device_hbm_limit_bytes",
                           {"device": f"tpu:{d}"}, 10e9)
    snap = eng.evaluate()
    assert snap["objectives"][0]["tiers"]["page"]["firing"], \
        snap["objectives"][0]
    # all devices healthy -> clears
    for _ in range(14):
        clk.advance(10.0)
        for d in range(4):
            s.ingest_gauge("device_hbm_used_bytes",
                           {"device": f"tpu:{d}"}, 4.0e9)
            s.ingest_gauge("device_hbm_limit_bytes",
                           {"device": f"tpu:{d}"}, 10e9)
    snap = eng.evaluate()
    assert not snap["objectives"][0]["tiers"]["page"]["firing"]


def test_firing_alert_resolves_when_objective_vanishes():
    """A paged objective whose series disappear (deployment deleted /
    LRU-evicted) resolves instead of burning forever."""
    clk = FakeClock(t0=360_000.0)
    s = _store(clk)
    obj = H.Objective(name="lat:gone", kind="latency",
                      metric="serve_proxy_handler_s",
                      labels={"deployment": "gone"}, threshold_s=0.25,
                      target=0.99, deployment="gone")
    eng = H.HealthEngine(s, _cfg(), clock=clk, objectives=[obj])
    cum = {"f": 0, "s": 0, "sum": 0.0}
    for _ in range(14):
        clk.advance(10.0)
        _push_lat(s, clk, "gone", 0, 10, cum)
    snap = eng.evaluate()
    assert snap["alerts"] and snap["alerts"][0]["tier"] == "page"
    # the objective disappears (user deregistration here; derived
    # objectives vanish the same way when their series evict)
    eng.objectives = []
    snap = eng.evaluate()
    assert snap["alerts"] == []
    assert ("lat:gone", "page", "resolved") in snap["transitions"]
    assert any(e.get("cat") == "health"
               and e.get("objective") == "lat:gone"
               and e.get("reason") == "objective gone"
               for e in events.dump())


def test_deactivate_clears_alert_gauges():
    """deactivate() zeroes the process-global alert/burn gauges — a
    later in-process cluster must not scrape a dead cluster's page as
    still firing."""
    m = H.health_metrics()
    m["active"].set(1.0, tags={"objective": "lat:x", "tier": "page"})
    m["burn"].set(55.0, tags={"objective": "lat:x", "tier": "page"})
    H.deactivate()
    assert m["active"]._values == {}
    assert m["burn"]._values == {}
    # and the cached catalog survives a metrics.reset() (identity
    # check rebuilds it against the fresh registry)
    first = H.health_metrics()
    assert H.health_metrics() is first


def test_consult_health_stamps_cache_before_rpc():
    """The shed advisory must not stampede the head: a stale cache is
    stamped BEFORE the RPC, so concurrent sheds (and post-failure
    retries) within the TTL skip the fetch."""
    from ray_tpu.serve.proxy import HTTPProxy
    p = HTTPProxy.__new__(HTTPProxy)
    p._health_advice = {"ts": 0.0, "state": None}
    # no cluster ctx: the fetch raises inside the advisory and is
    # swallowed — but the stamp must already be in place
    asyncio.run(p._consult_health("dep"))
    assert p._health_advice["ts"] > 0.0


def test_gauge_objective_worst_series_decides():
    """Per-series gauge evaluation: node A's healthy straggler gauge
    (-1) must not mask node B's stuck rank (the two push as distinct
    worker-labelled series)."""
    clk = FakeClock(t0=370_000.0)
    s = _store(clk)
    obj = H.Objective(name="strag", kind="gauge",
                      metric="allreduce_straggler_rank",
                      threshold=-0.5, direction="above")
    eng = H.HealthEngine(s, _cfg(), clock=clk, objectives=[obj])
    for _ in range(14):
        clk.advance(10.0)
        s.ingest_gauge("allreduce_straggler_rank",
                       {"worker": "a"}, -1.0)      # healthy node
        s.ingest_gauge("allreduce_straggler_rank",
                       {"worker": "b"}, 3.0)       # stuck rank
    snap = eng.evaluate()
    assert snap["objectives"][0]["tiers"]["page"]["firing"], \
        snap["objectives"][0]
    # burn gauge reflects the boolean breach as -1, not a stale value
    key = (("objective", "strag"), ("tier", "page"))
    assert eng._m["burn"]._values[key] == -1.0
    assert eng._m["active"]._values[key] == 1.0


def test_resolved_alerts_for_gone_objectives_are_pruned():
    clk = FakeClock(t0=380_000.0)
    s = _store(clk)
    obj = H.Objective(name="lat:churn", kind="latency",
                      metric="serve_proxy_handler_s",
                      labels={"deployment": "churn"}, threshold_s=0.25,
                      target=0.99, deployment="churn")
    eng = H.HealthEngine(s, _cfg(), clock=clk, objectives=[obj])
    cum = {"f": 0, "s": 0, "sum": 0.0}
    for _ in range(14):
        clk.advance(10.0)
        _push_lat(s, clk, "churn", 0, 10, cum)
    eng.evaluate()
    assert any(st["state"] == "firing"
               for st in eng._alerts.values())
    eng.objectives = []          # the objective churns away
    eng.evaluate()               # firing -> resolved
    # the dead objective's gauges are zeroed, not frozen mid-burn
    key = (("objective", "lat:churn"), ("tier", "page"))
    assert eng._m["active"]._values[key] == 0.0
    assert eng._m["burn"]._values[key] == 0.0
    eng.evaluate()               # resolved + gone -> pruned
    assert ("lat:churn", "page") not in eng._alerts
    assert ("lat:churn", "warn") not in eng._alerts


def test_health_json_param_parsed_not_substring_matched():
    from ray_tpu.util.metrics import _wants_json
    assert _wants_json("json=1")
    assert _wants_json("a=b&json=true")
    assert not _wants_json("json=0")
    assert not _wants_json("json=false")
    assert not _wants_json("fmt=jsonp")
    assert not _wants_json("")
    assert not _wants_json(None)


def test_derived_default_objectives_from_observed_series():
    clk = FakeClock(t0=400_000.0)
    s = _store(clk)
    cum = {"f": 0, "s": 0, "sum": 0.0}
    _push_lat(s, clk, "app1", 5, 0, cum)
    s.ingest_counter("serve_requests_total",
                     {"deployment": "app1", "code": "200"}, 5,
                     source="w1")
    s.ingest_gauge("allreduce_straggler_rank", None, -1.0)
    eng = H.HealthEngine(
        s, _cfg(slo_default_objectives=True,
                slo_latency_threshold_s=0.25, slo_target=0.999),
        clock=clk)
    names = {o.name: o for o in eng.active_objectives()}
    assert "latency:app1" in names and "availability:app1" in names
    assert "collective_straggler" in names
    assert names["latency:app1"].threshold_s == 0.25
    assert names["latency:app1"].target == 0.999
    # user-registered objective wins on name collision
    eng.add_objective(H.Objective(name="latency:app1", kind="latency",
                                  metric="serve_proxy_handler_s",
                                  threshold_s=9.0))
    names = {o.name: o for o in eng.active_objectives()}
    assert names["latency:app1"].threshold_s == 9.0
    # the off switch kills derivation
    eng2 = H.HealthEngine(s, _cfg(slo_default_objectives=False),
                          clock=clk)
    assert eng2.active_objectives() == []


def test_sentinels_compare_live_windows_to_pinned_baseline():
    clk = FakeClock(t0=500_000.0)
    s = _store(clk)
    baseline = {"sentinels": [{
        "name": "handler_p99", "metric": "serve_proxy_handler_s",
        "stat": "p99", "window_s": 120, "baseline": 0.2,
        "tolerance": 2.0, "source": "unit"}]}
    eng = H.HealthEngine(s, _cfg(), clock=clk, baseline=baseline)
    cum = {"f": 0, "s": 0, "sum": 0.0}
    for _ in range(6):
        clk.advance(10.0)
        _push_lat(s, clk, "a", 10, 0, cum)      # p99 ~0.1s: fine
    snap = eng.evaluate()
    row = snap["sentinels"][0]
    assert row["live"] is not None and not row["breached"]
    for _ in range(12):
        clk.advance(10.0)
        _push_lat(s, clk, "a", 0, 10, cum)      # p99 ~0.8s: 4x base
    snap = eng.evaluate()
    row = snap["sentinels"][0]
    assert row["breached"] and row["ratio"] > 2.0
    assert ("handler_p99", "sentinel", "firing") in snap["transitions"]
    assert any(e.get("cat") == "health" and e.get("name") == "sentinel"
               and e.get("sentinel") == "handler_p99"
               for e in events.dump())
    # the metric goes quiet: the sentinel resolves AND its gauge
    # zeroes instead of exporting the last breach ratio forever
    for _ in range(20):
        clk.advance(60.0)       # drain the 120s window entirely
    snap = eng.evaluate()
    row = snap["sentinels"][0]
    assert row["live"] is None and not row["breached"]
    assert ("handler_p99", "sentinel", "resolved") in \
        snap["transitions"]
    assert eng._m["sentinel"]._values[
        (("sentinel", "handler_p99"),)] == 0.0


def test_health_baseline_file_drift_fails_loudly():
    """Every committed HEALTH_BASELINE.json value must recompute from
    its source bench file — regenerating a bench without reseeding the
    baseline is a loud failure, not a silent regression-bar shift."""
    root = os.path.join(os.path.dirname(__file__), os.pardir)
    with open(os.path.join(root, "HEALTH_BASELINE.json")) as f:
        base = json.load(f)
    sent = {s["name"]: s for s in base["sentinels"]}
    assert {"serve_handler_p50", "serve_handler_p99", "llm_ttft_p50",
            "allreduce_round_mean"} <= set(sent)
    with open(os.path.join(root, "TRACE_BENCH.json")) as f:
        tb = json.load(f)
    best_on = max((r for r in tb["results"] if r["arm"] == "on"),
                  key=lambda r: r["req_per_s"])
    assert sent["serve_handler_p50"]["baseline"] == pytest.approx(
        best_on["p50_ms"] / 1e3, rel=1e-6)
    assert sent["serve_handler_p99"]["baseline"] == pytest.approx(
        best_on["p99_ms"] / 1e3, rel=1e-6)
    with open(os.path.join(root, "SERVE_BENCH.json")) as f:
        sb = json.load(f)
    assert sent["llm_ttft_p50"]["baseline"] == pytest.approx(
        sb["value"] / 1e3, rel=1e-6)
    with open(os.path.join(root, "ALLREDUCE_BENCH.json")) as f:
        ab = json.load(f)
    ring256 = [r["round_s"] for r in ab["results"]
               if r["mode"] == "ring" and r["size_mb"] == 256]
    assert ring256, "ALLREDUCE_BENCH lost its 256MB ring row"
    assert sent["allreduce_round_mean"]["baseline"] == pytest.approx(
        ring256[0], rel=1e-6)
    for s in base["sentinels"]:
        assert s["tolerance"] > 1.0 and s["window_s"] > 0
        assert s.get("source"), s["name"]


def test_snapshot_contract_for_autoscaler():
    """The /health JSON shape ROADMAP item 3's autoscaler consumes:
    stable top-level keys, per-deployment burn_advice, tier windows."""
    clk = FakeClock(t0=600_000.0)
    s = _store(clk)
    eng = H.HealthEngine(s, _cfg(), clock=clk)
    snap = eng.evaluate()
    for key in ("ts", "enabled", "series", "points_total", "tiers",
                "objectives", "alerts", "sentinels", "burn_advice",
                "eval_count", "transitions"):
        assert key in snap, key
    assert snap["enabled"] is True
    assert set(snap["tiers"]) == {"page", "warn"}
    for t in snap["tiers"].values():
        assert len(t["windows_s"]) == 2 and t["burn_threshold"] > 0
    json.dumps(snap)            # wire-serializable as-is
    # inactive process shape (the disabled half of the contract)
    H.deactivate()
    off = H.local_state()
    assert off["enabled"] is False and off.get("reason")


# --- config knobs / lint ----------------------------------------------------


def _load_linter():
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "scripts", "check_metrics_lint.py")
    spec = importlib.util.spec_from_file_location(
        "check_metrics_lint_zz", os.path.abspath(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_health_and_slo_knobs_exercised_and_linted():
    """Every health_*/slo_* Config knob is genuinely exercised here
    (the lint's coverage guarantee), including the pre-existing head
    liveness knobs the health_ prefix sweeps in."""
    cfg = Config.from_env(
        health_enabled=True, health_window_s=1.0,
        health_retention_s=120.0, health_max_series=64,
        health_baseline_path="HEALTH_BASELINE.json",
        health_check_period_s=1.0, health_check_failure_threshold=5,
        slo_eval_interval_s=0.5, slo_fast_burn=2.0,
        slo_fast_windows_s="3,6", slo_slow_burn=1.5,
        slo_slow_windows_s="6,30", slo_default_objectives=True,
        slo_latency_threshold_s=0.25, slo_target=0.95)
    assert cfg.health_max_series == 64
    assert cfg.health_check_failure_threshold == 5
    st = TimeSeriesStore(window_s=cfg.health_window_s,
                         retention_s=cfg.health_retention_s,
                         max_series=cfg.health_max_series,
                         clock=FakeClock())
    eng = H.HealthEngine(st, cfg, clock=st.clock)
    assert eng.tiers["page"]["windows"] == (3.0, 6.0)
    assert eng.tiers["page"]["burn"] == 2.0
    assert eng.tiers["warn"]["windows"] == (6.0, 30.0)
    assert eng.tiers["warn"]["burn"] == 1.5
    # malformed window specs fall back to defaults
    assert H._parse_windows("garbage", (1.0, 2.0)) == (1.0, 2.0)
    assert H._parse_windows("10,5", (1.0, 2.0)) == (1.0, 2.0)
    mod = _load_linter()
    assert {"health", "slo"} <= set(mod.KNOB_FAMILIES)
    assert mod.lint_knob_tests(families=["health", "slo"]) == []
    knobs = set(mod.family_knobs("health")) | set(
        mod.family_knobs("slo"))
    assert {"health_enabled", "health_window_s", "slo_fast_burn",
            "slo_fast_windows_s", "slo_eval_interval_s"} <= knobs


def test_health_event_category_and_metric_families_registered():
    mod = _load_linter()
    assert "health" in events.CATEGORIES
    assert "health" in events._CATEGORY_CAPS      # budget-capped
    assert mod.lint_category_caps() == []
    registry = mod.instantiate_all()
    for name in ("health_series", "health_points_total",
                 "health_eval_s", "health_sentinel_ratio",
                 "slo_burn_rate", "slo_alerts_total",
                 "slo_alert_active"):
        assert name in registry, name
    assert mod.lint(registry) == []
    # the family scan covers health_/slo_ literals now
    assert set(mod.METRIC_FAMILY_PREFIXES) >= {"health_", "slo_"}
    assert mod.lint_device_metric_registration(registry) == []


def test_lint_requires_nonempty_descriptions():
    mod = _load_linter()

    class _Fake:
        def __init__(self, kind, description=None):
            self.kind = kind
            if description is not None:
                self.description = description

    errs = mod.lint({
        "described_total": _Fake("counter", "counts things"),
        "undocumented_total": _Fake("counter", ""),
        "whitespace_total": _Fake("counter", "   "),
        "legacy_total": _Fake("counter"),     # no attr: not a Metric
    })
    assert any("undocumented_total" in e and "description" in e
               for e in errs)
    assert any("whitespace_total" in e for e in errs)
    assert not any("described_total" in e for e in errs)
    assert not any("legacy_total" in e for e in errs)


# --- satellite: worker final metrics flush ----------------------------------


def test_push_once_sends_labeled_snapshot():
    M.Counter("zz_health_flush_total", "flush test").inc(3.0)
    calls = []

    async def call(method, **kw):
        calls.append((method, kw))

    async def go():
        return await M.push_once(call, "worker:abc",
                                 {"node": "n1", "worker": "abc"})

    assert asyncio.run(go()) is True
    assert calls and calls[0][0] == "report_metrics"
    kw = calls[0][1]
    assert kw["source"] == "worker:abc"
    assert 'zz_health_flush_total{node="n1",worker="abc"} 3' \
        in kw["text"]


def test_shutdown_worker_drains_final_metrics_push():
    """Graceful shutdown flushes events AND one final metrics snapshot
    (the push loop's last interval must not die with the worker); a
    hanging head bounds the flush instead of stalling exit."""
    from ray_tpu.runtime.worker import WorkerExecutor

    done = {"events": False, "metrics": False}

    class _Stub:
        async def flush_events(self):
            done["events"] = True

        async def _final_metrics_push(self):
            done["metrics"] = True

    stub = _Stub()

    async def go():
        return await WorkerExecutor.shutdown_worker(stub)

    r = asyncio.run(go())
    assert r == {"ok": True}
    assert done["events"] and done["metrics"]

    # a stub WITHOUT the flush attr (old workers / driver-attached
    # executors) still shuts down cleanly
    class _Bare:
        async def flush_events(self):
            pass

    assert asyncio.run(
        WorkerExecutor.shutdown_worker(_Bare())) == {"ok": True}

    # and a hanging push is bounded by the wait_for, not fatal
    class _Hang:
        async def flush_events(self):
            pass

        async def _final_metrics_push(self):
            await asyncio.sleep(30.0)

    t0 = time.monotonic()
    assert asyncio.run(
        WorkerExecutor.shutdown_worker(_Hang())) == {"ok": True}
    assert time.monotonic() - t0 < 5.0


# --- surfaces: chrome lane, CLI helpers, proxy advisory ---------------------


def test_to_chrome_renders_health_instants():
    from ray_tpu.util.tracing import to_chrome
    evs = [
        {"cat": "health", "name": "alert", "ts": 100.0,
         "objective": "latency:a", "tier": "page", "state": "firing",
         "burn_short": 50.0, "burn_long": 20.0, "trace": "feed5",
         "node": "n1"},
        {"cat": "health", "name": "sentinel", "ts": 101.0,
         "sentinel": "handler_p99", "state": "resolved",
         "live": 0.1, "baseline": 0.2, "node": "n1"},
    ]
    recs = to_chrome(evs)
    inst = [r for r in recs if r.get("cat") == "health"]
    assert len(inst) == 2
    assert all(r["ph"] == "I" and r["tid"] == "health" for r in inst)
    assert inst[0]["name"] == "page:latency:a:firing"
    assert inst[0]["args"]["trace"] == "feed5"
    assert inst[1]["name"] == "sentinel:handler_p99:resolved"


def test_parse_since_and_spark():
    assert H.parse_since("90s") == 90.0
    assert H.parse_since("15m") == 900.0
    assert H.parse_since("2h") == 7200.0
    assert H.parse_since("45") == 45.0
    assert H.parse_since("junk", 123.0) == 123.0
    line = H.spark([0, 1, 2, 3, 4, 5, 6, 7])
    assert line[0] == "▁" and line[-1] == "█"
    assert H.spark([]) == "(no data)"
    assert len(H.spark(list(range(500)))) <= 48
    assert len(H.spark([5.0])) == 1
    # decimation is MAX-aggregated: a single spike survives the fit
    flat = [1.0] * 120
    flat[57] = 100.0
    assert "█" in H.spark(flat)


def test_proxy_shed_advisory_logs_when_burning(caplog):
    """Log-only advisory: a shed while the health plane reports the
    deployment's budget burning names the autoscaler hook; a healthy
    or absent snapshot stays silent. (Cache pre-seeded: no RPC.)"""
    import logging

    from ray_tpu.serve.proxy import HTTPProxy
    p = HTTPProxy.__new__(HTTPProxy)        # skip actor init
    p._health_advice = {
        "ts": time.monotonic(),
        "state": {"burn_advice": {"app1": {
            "availability_burning": True, "latency_burning": False,
            "tier": "page"}}}}
    with caplog.at_level(logging.WARNING, logger="ray_tpu.serve.proxy"):
        asyncio.run(p._consult_health("app1"))
    assert any("autoscaler hook" in r.message for r in caplog.records)
    caplog.clear()
    # rate-limited: a shed storm gets ONE line per cache window
    with caplog.at_level(logging.WARNING, logger="ray_tpu.serve.proxy"):
        asyncio.run(p._consult_health("app1"))
    assert not caplog.records
    with caplog.at_level(logging.WARNING, logger="ray_tpu.serve.proxy"):
        asyncio.run(p._consult_health("quiet_dep"))
    assert not caplog.records


def test_cli_health_and_metrics_query(monkeypatch, capsys):
    from ray_tpu import scripts as S
    state = {
        "enabled": True, "series": 4, "points_total": 99,
        "eval_count": 7,
        "tiers": {"page": {"windows_s": [60, 300],
                           "burn_threshold": 14.4},
                  "warn": {"windows_s": [300, 1800],
                           "burn_threshold": 3.0}},
        "alerts": [{"objective": "latency:a", "tier": "page",
                    "state": "firing", "since": 1000.0,
                    "exemplar": "deadbeef"}],
        "objectives": [{
            "name": "latency:a", "kind": "latency",
            "metric": "serve_proxy_handler_s", "alert": "page",
            "tiers": {"page": {"burn_short": 55.0, "burn_long": 21.0},
                      "warn": {"burn_short": None,
                               "burn_long": None}}}],
        "sentinels": [{"name": "p99", "metric": "m", "stat": "p99",
                       "window_s": 300.0, "baseline": 0.2,
                       "tolerance": 2.0, "live": 0.9, "ratio": 4.5,
                       "breached": True}],
        "burn_advice": {"a": {"availability_burning": False,
                              "latency_burning": True,
                              "tier": "page"}},
    }
    series = {"name": "serve_proxy_handler_s", "kind": "histogram",
              "window_s": 10.0, "series": 2,
              "points": [{"t": 0.0, "count": 5, "rate": 0.5,
                          "mean": 0.2, "p50": 0.1, "p99": 0.4},
                         {"t": 10.0, "count": 9, "rate": 0.9,
                          "mean": 0.5, "p50": 0.4, "p99": 0.9}]}

    def fake_call(addr, method, timeout=10.0, **kw):
        return state if method == "health_state" else series

    monkeypatch.setattr(S, "_call_head", fake_call)
    monkeypatch.setattr(S, "_resolve_address", lambda a: "h:1")
    assert S.main(["health"]) == 0
    out = capsys.readouterr().out
    assert "ALERT [PAGE] latency:a" in out
    assert "ray-tpu trace deadbeef" in out
    assert "REGRESSION" in out and "4.50x" in out
    assert S.main(["health", "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["enabled"] is True
    assert S.main(["metrics", "serve_proxy_handler_s",
                   "--since", "15m"]) == 0
    out = capsys.readouterr().out
    assert "histogram" in out and "p99" in out
    assert S.main(["metrics", "serve_proxy_handler_s", "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["kind"] == "histogram"
    # disabled plane: query explains instead of stack-tracing
    monkeypatch.setattr(
        S, "_call_head",
        lambda *a, **k: {"error": "health plane inactive"})
    assert S.main(["metrics", "x_total"]) == 1


# --- live-cluster e2e -------------------------------------------------------


@pytest.fixture(scope="module")
def health_cluster():
    """A cluster tuned for seconds-scale SLO windows, with chaos delay
    armed at the replica for requests 11..60 — the injected TTFT
    degradation phase (healthy before, recovered after)."""
    delays = ",".join(f"replica:delay:{n}:0.8" for n in range(11, 61))
    env = {
        "RAY_TPU_METRICS_EXPORT_INTERVAL_S": "0.5",
        "RAY_TPU_HEALTH_WINDOW_S": "1.0",
        "RAY_TPU_HEALTH_RETENTION_S": "120",
        "RAY_TPU_SLO_EVAL_INTERVAL_S": "0.5",
        "RAY_TPU_SLO_FAST_WINDOWS_S": "3,8",
        "RAY_TPU_SLO_FAST_BURN": "5",
        "RAY_TPU_SLO_SLOW_WINDOWS_S": "8,30",
        "RAY_TPU_SLO_LATENCY_THRESHOLD_S": "0.25",
        "RAY_TPU_METRICS_PORT": "0",
        "RAY_TPU_TESTING_SERVE_FAILURE": delays,
    }
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    import ray_tpu
    ray_tpu.init(num_cpus=8)
    yield
    from ray_tpu import serve
    serve.shutdown()
    ray_tpu.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _head_call(method, **kw):
    from ray_tpu import api
    ctx = api._require_init()
    return api._run(ctx.pool.call(ctx.head_addr, method,
                                  timeout=10.0, **kw))


def _post(addr, path, payload):
    conn = http.client.HTTPConnection(addr["host"], addr["port"],
                                      timeout=30)
    conn.request("POST", path, body=json.dumps(payload),
                 headers={"Content-Type": "application/json"})
    r = conn.getresponse()
    r.read()
    status = r.status
    conn.close()
    return status


@pytest.mark.slow
def test_ttft_degradation_fires_page_alert_with_trace_e2e(
        health_cluster):
    """The acceptance walk: chaos delay at the replica degrades TTFT →
    the fast-burn page-tier alert fires within its detection window,
    its event carries an exemplar trace id that resolves in the
    timeline (`ray-tpu trace <id>`), recovery clears the alert, and
    the /health?json=1 endpoint serves the same machine contract."""
    from ray_tpu import serve

    @serve.deployment(max_ongoing_requests=4, num_replicas=1)
    class Echo:
        async def __call__(self, v=None):
            return {"ok": True}

    serve.run(Echo.bind(), name="app_slo", route_prefix="/slo")
    addr = serve.proxy_address()
    dep = None

    # phase 1: 10 healthy requests (chaos arms at the 11th)
    for _ in range(10):
        assert _post(addr, "/slo", {"x": 1}) == 200

    # phase 2: degraded traffic (0.8s chaos delay per request) from
    # background threads while we poll the health plane for the page
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            try:
                _post(addr, "/slo", {"x": 1})
            except Exception:
                time.sleep(0.2)

    threads = [threading.Thread(target=pump, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    fired = None
    deadline = time.monotonic() + 45.0
    try:
        while time.monotonic() < deadline:
            s = _head_call("health_state")
            if s.get("enabled"):
                for a in s.get("alerts", []):
                    if a["tier"] == "page" and \
                            a["objective"].startswith("latency:"):
                        fired = a
                        dep = a["objective"].split(":", 1)[1]
                        break
            if fired:
                break
            time.sleep(0.5)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
    assert fired is not None, \
        f"page alert never fired; last state: {json.dumps(s)[:800]}"
    assert dep == "Echo"       # proxy tags by DEPLOYMENT name

    # the alert's exemplar trace id resolves in the cluster timeline
    ex = fired.get("exemplar")
    assert ex, fired
    from ray_tpu.util.tracing import filter_trace
    tl = _head_call("collect_timeline")
    mine = filter_trace(tl.get("events", []), ex)
    assert mine, f"exemplar trace {ex} not resolvable in the timeline"
    assert any(e.get("cat") == "request" for e in mine)
    # and the firing transition is a "health" event in the timeline
    assert any(e.get("cat") == "health" and e.get("state") == "firing"
               and str(e.get("objective", "")).startswith("latency:")
               for e in tl.get("events", []))

    # the machine-readable endpoint serves the same contract
    from ray_tpu import api
    maddr = getattr(api._g.head, "metrics_addr", None)
    if maddr:
        conn = http.client.HTTPConnection(maddr[0], maddr[1],
                                          timeout=10)
        conn.request("GET", "/health?json=1")
        r = conn.getresponse()
        doc = json.loads(r.read())
        conn.close()
        assert doc.get("enabled") is True
        assert "burn_advice" in doc and "objectives" in doc

    # phase 3: recovery — chaos rules exhausted, healthy traffic
    # drains both burn windows and the alert resolves
    resolved = False
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        _post(addr, "/slo", {"x": 1})
        s = _head_call("health_state")
        active = [a for a in s.get("alerts", [])
                  if a["tier"] == "page"
                  and a["objective"] == f"latency:{dep}"]
        if not active:
            resolved = True
            break
        time.sleep(0.5)
    assert resolved, "page alert never cleared after recovery"
    # the resolved transition joined the health event stream too
    tl = _head_call("collect_timeline")
    assert any(e.get("cat") == "health" and e.get("state") == "resolved"
               and e.get("objective") == f"latency:{dep}"
               for e in tl.get("events", []))
    serve.delete("app_slo")


@pytest.mark.slow
def test_worker_pushed_series_reach_head_store_e2e(health_cluster):
    """A counter incremented inside a worker becomes queryable history
    at the head (push_loop -> report_metrics -> timeseries ingest ->
    query_series) — the aggregation path the final graceful-shutdown
    flush (unit-tested above) drains through."""
    import ray_tpu

    # an ACTOR pins both increments to one worker process: the first
    # push containing the series is the store's baseline, so only the
    # SECOND bump's delta is expected to land in windows
    @ray_tpu.remote
    class Bumper:
        def bump(self):
            from ray_tpu.util import metrics as m
            m.Counter("zz_flush_e2e_total",
                      "push-path e2e").inc(7.0)
            return os.getpid()

    b = Bumper.remote()
    ray_tpu.get(b.bump.remote())
    time.sleep(1.5)             # > export interval: baseline push out
    ray_tpu.get(b.bump.remote())
    deadline = time.monotonic() + 15.0
    found = None
    while time.monotonic() < deadline:
        r = _head_call("query_series", name="zz_flush_e2e_total",
                       since_s=60.0)
        if r.get("points"):
            found = r
            break
        time.sleep(0.5)
    assert found, "pushed counter never reached the head store"
    assert sum(p["inc"] for p in found["points"]) >= 7.0
