"""SIGTERM final-flush e2e: the agent reaps workers with SIGTERM
(agent._kill_worker -> proc.terminate()), so the graceful-shutdown
drain (span flush + ONE final metrics push, runtime/worker.py) must
run on that signal — not only on the shutdown_worker RPC nothing in
production invokes. The export interval is set far beyond the test's
lifetime, so the victim's counters can ONLY reach the head through
the final flush. (Own module: it needs a cluster whose push cadence
differs from test_zz_health's; late-alphabet name keeps the tier-1
cutoff stable.)"""

import os
import signal
import time

import pytest


@pytest.fixture(scope="module")
def term_cluster():
    env = {"RAY_TPU_METRICS_EXPORT_INTERVAL_S": "30"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    import ray_tpu
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


@pytest.mark.slow
def test_sigterm_drains_final_metrics_snapshot_e2e(term_cluster):
    import ray_tpu
    from ray_tpu.util import metrics as M

    @ray_tpu.remote
    class Bumper:
        def bump(self):
            from ray_tpu.util import metrics as m
            m.Counter("zz_term_flush_total",
                      "sigterm final-flush e2e").inc(5.0)
            return os.getpid()

    b = Bumper.remote()
    pid = ray_tpu.get(b.bump.remote())
    # nothing has pushed (30s export interval): the head's aggregated
    # view must not know the counter yet — otherwise the assertion
    # below would pass without the final flush
    assert "zz_term_flush_total" not in M.render_all()
    os.kill(pid, signal.SIGTERM)
    deadline = time.monotonic() + 10.0
    text = ""
    while time.monotonic() < deadline:
        text = M.render_all()      # driver IS the head (in-process)
        if "zz_term_flush_total" in text:
            break
        time.sleep(0.25)
    line = next((ln for ln in text.splitlines()
                 if ln.startswith("zz_term_flush_total")), None)
    assert line is not None, \
        "SIGTERM'd worker's final snapshot never reached the head"
    assert line.endswith(" 5"), line
