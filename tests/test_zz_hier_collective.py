"""Topology-aware hierarchical collectives (dag/ring.py
HierarchicalReducer), bucketed gradient sync (train/collective.py),
and the in-situ auto-tuner (dag/tuner.py): ring-of-rings parity vs the
flat ring, zero-size shards, leader death mid-inter-ring, bucketed ==
unbucketed, tuner bands + cache invalidation per ring generation.
Channel-level with thread participants (tier-1, CPU), like
test_zero_collective_ops.py.

Named late in the alphabet ON PURPOSE: tier-1 is wall-clock bounded
(870s DOTS_PASSED cutoff) and new modules must not shift earlier
modules out of the window.
"""

import json
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from ray_tpu.dag import tuner
from ray_tpu.dag.channel import ShmRingChannel
from ray_tpu.dag.ring import (HierarchicalReducer, RingPeerDead,
                              RingReducer, hier_seg_bounds)
from ray_tpu.util import events


@pytest.fixture(autouse=True)
def _clean_tuner_and_events():
    tuner.invalidate()
    events.clear()
    yield
    tuner.invalidate()
    events.clear()


def _mk_chan():
    return ShmRingChannel(create=True, nslots=4, slot_bytes=1 << 20)


def _make_hier(counts, timeout=5.0, group="hg", **inter_kw):
    """Thread-shaped 2-level group: one intra shm ring per multi-rank
    node, one shm "inter" ring over the leaders (transport is opaque
    to the reducers). Yields the world's HierarchicalReducers."""
    L = len(counts)
    intra_ch = {i: [_mk_chan() for _ in range(k)] if k > 1 else []
                for i, k in enumerate(counts)}
    inter_ch = [_mk_chan() for _ in range(L)]
    chans = [c for v in intra_ch.values() for c in v] + inter_ch
    reds = []
    for i, k in enumerate(counts):
        for j in range(k):
            intra = None
            if k > 1:
                intra = RingReducer(
                    intra_ch[i][j], intra_ch[i][(j - 1) % k],
                    rank=j, size=k, timeout_s=timeout,
                    group=f"{group}.n{i}", level="intra")
            inter = None
            if j == 0:
                inter = RingReducer(
                    inter_ch[i], inter_ch[(i - 1) % L],
                    rank=i, size=L, timeout_s=timeout,
                    group=f"{group}.x", level="inter", **inter_kw)
            reds.append(HierarchicalReducer(
                node=i, local=j, node_counts=counts, intra=intra,
                inter=inter, op="mean", timeout_s=timeout, group=group))
    try:
        yield reds
    finally:
        for c in chans:
            c.close()
            c.unlink()


def _make_flat(n, timeout=5.0, **kw):
    chans = [_mk_chan() for _ in range(n)]
    reds = [RingReducer(chans[r], chans[(r - 1) % n], rank=r, size=n,
                        timeout_s=timeout, **kw) for r in range(n)]
    try:
        yield reds
    finally:
        for c in chans:
            c.close()
            c.unlink()


def _all(reds, fn):
    with ThreadPoolExecutor(len(reds)) as ex:
        return list(ex.map(fn, reds))


def _int_vals(n_ranks, n_el=1003, extra=5):
    """Integer-valued fp32 pytrees: sums are exact in any association
    order, so the flat ring and the ring-of-rings must agree BITWISE."""
    rng = np.random.default_rng(7)
    return [{"w": np.round(rng.standard_normal(n_el) * 8)
             .astype(np.float32),
             "b": np.arange(extra, dtype=np.float32) * (r + 1)}
            for r in range(n_ranks)]


# --- topology / bounds ---------------------------------------------------


def test_hier_seg_bounds_tile_and_nest():
    """The nested two-level split tiles the flat space for even AND
    uneven node shapes, and nests with the sub-rings' own splits
    (which the flat N-way split provably does not, e.g. total=2 over
    3x2 ranks)."""
    for total in (0, 1, 2, 5, 17, 1003, 12345):
        for counts in ([2, 2], [3, 1], [2, 2, 2], [1, 1], [4, 2, 1]):
            n = sum(counts)
            bounds = [hier_seg_bounds(total, counts, r)
                      for r in range(n)]
            assert bounds[0][0] == 0 and bounds[-1][1] == total
            for (a, b), (c, d) in zip(bounds, bounds[1:]):
                assert a <= b == c <= d
    with pytest.raises(ValueError, match="out of range"):
        hier_seg_bounds(10, [2, 2], 4)


# --- parity vs the flat ring ---------------------------------------------


def test_hier_allreduce_bitwise_parity_vs_flat_2x2():
    """2 nodes x 2 ranks: fused hierarchical mean equals the flat
    ring's BITWISE on exactly-representable data, and all ranks are
    bitwise identical to each other."""
    gen = _make_hier([2, 2])
    reds = next(gen)
    vals = _int_vals(4)
    outs = _all(reds, lambda g: g.reduce(vals[g.rank], op="mean"))
    fgen = _make_flat(4)
    flat = next(fgen)
    fouts = _all(flat, lambda g: g.reduce(vals[g.rank], op="mean"))
    for o in outs:
        assert np.array_equal(o["w"], fouts[0]["w"])
        assert np.array_equal(o["b"], fouts[0]["b"])
        assert o["w"].dtype == np.float32
    gen.close()
    fgen.close()


def test_hier_codecs_on_inter_leg_bitwise_identical_across_ranks():
    """int8 / int4 / bf16 wire codecs ride the cross-node leg only:
    results stay bitwise identical across ALL ranks (owner round-trip
    + verbatim broadcast), and each codec's error stays within its
    documented (L * max_scale)/2-style bound (int4's 15-level blocks
    are ~18x coarser than int8's — hence the looser pin)."""
    vals = _int_vals(4, n_el=2048, extra=0)
    exact = sum(v["w"].astype(np.float64) for v in vals) / 4
    for codec_kw, tol in (({"quantize": "int8"}, 0.25),
                          ({"quantize": "int4"}, 3.0),
                          ({"wire_dtype": "bfloat16"}, 0.25)):
        gen = _make_hier([2, 2])
        reds = next(gen)
        outs = _all(reds, lambda g: g.reduce(
            vals[g.rank], op="mean", **codec_kw))
        for o in outs[1:]:
            assert np.array_equal(o["w"], outs[0]["w"])
        err = np.abs(outs[0]["w"].astype(np.float64) - exact).max()
        assert err < tol, (codec_kw, err)    # quantized, not garbage
        gen.close()
    # fp32 control: exact
    gen = _make_hier([2, 2])
    reds = next(gen)
    outs = _all(reds, lambda g: g.reduce(vals[g.rank], op="mean"))
    assert np.array_equal(outs[0]["w"], exact.astype(np.float32))
    gen.close()


def test_hier_reduce_scatter_allgather_roundtrip_uneven_nodes():
    """Standalone RS -> AG over an UNEVEN 3+1 topology: shards tile
    the flat space at hier_seg_bounds, the allgather rebuilds the full
    pytree with input leaf dtypes."""
    counts = [3, 1]
    gen = _make_hier(counts)
    reds = next(gen)
    vals = _int_vals(4)
    shards = _all(reds, lambda g: g.reduce_scatter(
        vals[g.rank], op="sum"))
    total = 1008
    exact = np.concatenate(
        [sum(v["w"].astype(np.float64) for v in vals),
         sum(v["b"].astype(np.float64) for v in vals)])
    for r, s in enumerate(shards):
        lo, hi = hier_seg_bounds(total, counts, r)
        assert s.size == hi - lo
        assert np.array_equal(np.asarray(s, np.float64), exact[lo:hi])
    fulls = _all(reds, lambda g: g.allgather(shards[g.rank]))
    for f in fulls:
        assert np.array_equal(
            f["w"], exact[:1003].astype(np.float32))
        assert f["b"].dtype == np.float32
    gen.close()


def test_hier_zero_size_shards():
    """total < world size: some ranks own empty shards; the round
    completes and reassembles exactly (the satellite's degenerate
    case)."""
    gen = _make_hier([2, 2])
    reds = next(gen)
    tiny = [np.arange(2, dtype=np.float32) * (r + 1) for r in range(4)]
    shards = _all(reds, lambda g: g.reduce_scatter(
        tiny[g.rank], op="sum"))
    assert sorted(s.size for s in shards) == [0, 0, 1, 1]
    assert np.array_equal(np.concatenate(shards), sum(tiny))
    fulls = _all(reds, lambda g: g.allgather(shards[g.rank]))
    for f in fulls:
        assert np.array_equal(f, sum(tiny))
    gen.close()


# --- failure: leader death mid-inter-ring --------------------------------


def test_leader_death_mid_inter_ring_surfaces_everywhere(tmp_path):
    """Node B's leader dies AFTER the intra legs, i.e. entering the
    inter ring: every surviving rank — the other leader, its member,
    and the dead leader's own member — surfaces RingPeerDead with a
    flight-recorder dump attached."""
    from ray_tpu.config import get_config
    cfg = get_config()
    saved = cfg.collective_flight_dir
    cfg.collective_flight_dir = str(tmp_path)
    try:
        gen = _make_hier([2, 2], timeout=2.0, group="death")
        reds = next(gen)
        vals = _int_vals(4)

        def run(g):
            if g.rank == 2:   # node B's leader: intra legs, then dies
                # the real path stages a flat vector before the legs
                flat = np.concatenate(
                    [vals[2]["w"], vals[2]["b"]]).astype(np.float32)
                ish = g.intra.reduce_scatter(flat, op="sum")
                g.intra.allgather(ish, rebuild=False)
                return "died"
            with pytest.raises(RingPeerDead) as ei:
                g.reduce_scatter(vals[g.rank], op="mean")
            return ei.value

        outs = _all(reds, run)
        for r, out in enumerate(outs):
            if r == 2:
                assert out == "died"
                continue
            path = getattr(out, "flight_recorder_path", None)
            assert path, f"rank {r} has no flight dump"
            with open(path) as f:
                dump = json.load(f)
            assert dump["rounds"], f"rank {r} dump is empty"
        gen.close()
    finally:
        cfg.collective_flight_dir = saved


# --- level tags / span hygiene -------------------------------------------


def test_spans_carry_level_tags_and_distinct_groups():
    """Sub-ring spans tag their hierarchy level (intra/inter; the
    fan-out phase tags bcast) under DISTINCT group ids, so chrome
    lanes and straggler attribution cannot cross-wire the levels; the
    collectives table surfaces the level column."""
    gen = _make_hier([2, 2], group="lv")
    reds = next(gen)
    vals = _int_vals(4, n_el=512, extra=0)
    _all(reds, lambda g: g.reduce(vals[g.rank], op="mean"))
    evs = [e for e in events.dump() if e.get("cat") == "collective"
           and e.get("name") == "round"]
    levels = {e.get("level") for e in evs}
    assert {"intra", "inter", "bcast"} <= levels, levels
    by_level_groups = {}
    for e in evs:
        by_level_groups.setdefault(e.get("level"), set()).add(
            e.get("group"))
    assert by_level_groups["inter"] == {"lv.x"}
    assert by_level_groups["intra"] == {"lv.n0", "lv.n1"}
    # bcast rounds ride the intra rings' groups
    assert by_level_groups["bcast"] <= {"lv.n0", "lv.n1"}
    from ray_tpu.util.state import collectives_from_events
    rows = collectives_from_events(evs, limit=1000)
    assert {"intra", "inter", "bcast"} <= {r["level"] for r in rows}
    assert any(r["kind"] == "broadcast" for r in rows)
    gen.close()


# --- bucketed gradient sync ----------------------------------------------


def test_bucket_parts_deterministic_and_order_preserving():
    from ray_tpu.train.collective import _bucket_parts
    leaves = [np.zeros(100, np.float32), np.zeros(300, np.float32),
              np.zeros(10, np.float32), np.zeros(5000, np.float32),
              np.zeros(1, np.float32)]
    parts = _bucket_parts(leaves, 2000)
    # 400+1200+40 pack; the 20000B leaf rides alone; the tail closes
    assert parts == [(0, 3), (3, 4), (4, 5)]
    assert sum(b - a for a, b in parts) == len(leaves)
    assert parts == _bucket_parts(leaves, 2000)   # deterministic
    assert _bucket_parts(leaves, 1) == [(i, i + 1)
                                        for i in range(len(leaves))]
    with pytest.raises(ValueError, match="bucket_bytes"):
        _bucket_parts(leaves, 0)


def test_bucketed_allreduce_bitwise_equals_unbucketed():
    """On exactly-representable data (sums exact in any association
    order) the bucketed sync is bitwise identical to the unbucketed
    one — bucketing only changes WHEN bytes move — and the hidden
    staging time lands in allreduce_bucket_overlap_s."""
    from ray_tpu.dag.ring import allreduce_metrics
    from ray_tpu.train.collective import _bucketed_allreduce
    rng = np.random.default_rng(3)
    vals = [{"a": np.round(rng.standard_normal(4096) * 8)
             .astype(np.float32),
             "b": np.round(rng.standard_normal(333) * 8)
             .astype(np.float32),
             "c": np.float32(r + 1)} for r in range(3)]
    gen = _make_flat(3)
    reds = next(gen)
    base = _all(reds, lambda g: g.reduce(vals[g.rank], op="mean"))
    gen.close()
    m = allreduce_metrics()["bucket_overlap"]
    count0 = sum(sum(c) for c in m._counts.values())
    gen = _make_flat(3)
    reds = next(gen)
    outs = _all(reds, lambda g: _bucketed_allreduce(
        g, vals[g.rank], "mean", None, None, 4096))
    gen.close()
    for o, b in zip(outs, base):
        assert np.array_equal(o["a"], b["a"])
        assert np.array_equal(o["b"], b["b"])
        assert isinstance(o["c"], float) and o["c"] == b["c"]
    # the overlap histogram saw the sync (one observation per rank)
    assert sum(sum(c) for c in m._counts.values()) >= count0 + 3


def test_bucketed_zero_optimizer_matches_unbucketed():
    """ShardedOptimizer(bucket_bytes=...) produces bitwise-identical
    parameters to the unbucketed optimizer — per-bucket shards change
    the partitioning, not the math — and refuses the elastic surfaces
    that assume one contiguous shard."""
    optax = pytest.importorskip("optax")
    from ray_tpu.train import reshard as _rs
    from ray_tpu.train.zero import ShardedOptimizer
    rng = np.random.default_rng(11)
    params = rng.standard_normal(3000).astype(np.float32)
    # integer-valued grads: the mean's sum is exact in any association
    # order, so the two partitionings must agree BITWISE
    grads = [np.round(rng.standard_normal(3000) * 8).astype(np.float32)
             for _ in range(3)]

    def run(bucket_bytes):
        gen = _make_flat(3)
        reds = next(gen)

        def one(g):
            so = ShardedOptimizer(optax.adamw(1e-3), group=g,
                                  bucket_bytes=bucket_bytes)
            state = so.init(params)
            p = params
            for _ in range(2):
                p, state = so.update(grads[g.rank], state, p)
            return p
        outs = _all(reds, one)
        gen.close()
        return outs

    base = run(None)
    bucketed = run(2048)
    for b, u in zip(bucketed, base):
        assert np.array_equal(np.asarray(b), np.asarray(u))
    with pytest.raises(ValueError, match="mirror"):
        ShardedOptimizer(optax.adamw(1e-3), bucket_bytes=1024,
                         mirror_interval_steps=1)
    so = ShardedOptimizer(optax.adamw(1e-3), bucket_bytes=1024)
    with pytest.raises(_rs.ReshardError, match="bucketed"):
        so.reshard(None)


# --- the in-situ auto-tuner ----------------------------------------------


def test_tuner_bands_star_ring_hier():
    """A registered profile drives the three-regime decision: star
    below the measured crossover, flat ring in the middle band,
    hierarchical on top when the topology exists — and the regime
    gauge records each decision."""
    from ray_tpu.dag.ring import allreduce_metrics
    tuner.register_profile("t1", 4, alpha_s=0.01,
                           beta_s_per_b=1e-9, hierarchical=True)
    s_star = tuner.star_crossover(4, 0.01, 1e-9)
    s_hier = tuner.hier_crossover(4, 0.01, 1e-9)
    assert 64 * 1024 <= s_star <= 64 << 20
    assert s_hier >= max(8 << 20, s_star)
    g = allreduce_metrics()["tuner_regime"]
    assert tuner.choose_impl(s_star // 2, 4, key="t1") == "star"
    assert g._values[()] == 0
    assert tuner.choose_impl(
        (s_star + s_hier) // 2, 4, key="t1") == "ring"
    assert g._values[()] == 1
    assert tuner.choose_impl(2 * s_hier, 4, hierarchical=True,
                             key="t1") == "hier"
    assert g._values[()] == 2
    # no topology -> never hier, whatever the payload
    assert tuner.choose_impl(2 * s_hier, 4, key="t1") == "ring"
    # unknown key, no default fallback match for a different size
    assert tuner.choose_impl(1 << 20, 8, key="t1") is None
    rows = tuner.table("t1", 4, hierarchical=True)
    assert [r["impl"] for r in rows] == ["star", "ring", "hier"]


def test_tuner_chunk_clamped_to_floor_and_slot():
    tuner.register_profile("t2", 4, alpha_s=0.009, beta_s_per_b=1e-9)
    small = tuner.tuned_chunk("t2", 4, 256 * 1024, 1 << 20)
    big = tuner.tuned_chunk("t2", 4, 1 << 30, 2 << 20)
    assert small is not None and 4096 <= small <= 1 << 20
    assert big == 2 << 20                      # clamped to the slot
    assert tuner.tuned_chunk("nope", 4, 1 << 20, 1 << 20) is None


def test_tuner_probes_in_situ_and_invalidates_per_generation():
    """A tuning-enabled ring probes itself at the FIRST collective
    (two tiny fused rounds, identical on every rank), caches under its
    group id, and a new ring generation (fresh group id — what the
    controller mints per incarnation) re-probes; invalidate() drops
    the cache explicitly."""
    vals = [np.round(np.random.default_rng(r).standard_normal(512) * 4)
            .astype(np.float32) for r in range(3)]

    def run(group):
        gen = _make_flat(3, group=group, tune=True)
        reds = next(gen)
        outs = _all(reds, lambda g: g.reduce(vals[g.rank], op="sum"))
        gen.close()
        return outs

    assert tuner.profile_for("gen1", 3) is None
    outs = run("gen1")
    exact = sum(v.astype(np.float64) for v in vals)
    for o in outs:
        assert np.array_equal(o, exact.astype(np.float32))
    prof1 = tuner.profile_for("gen1", 3)
    assert prof1 is not None and prof1["alpha_s"] > 0
    # generation bump: a NEW group id has no profile -> re-probes
    assert tuner.profile_for("gen2", 3) is None
    run("gen2")
    prof2 = tuner.profile_for("gen2", 3)
    assert prof2 is not None and prof2 is not prof1
    # explicit invalidation
    tuner.invalidate("gen2")
    assert tuner.profile_for("gen2", 3) is None
    tuner.invalidate()
    assert tuner.profile_for("gen1", 3) is None


def test_tuner_payload_hint_cached_from_layout():
    """The per-round tuned-chunk lookup derives the payload hint from
    the already-flattened layout (ring._payload_hint) instead of
    re-flattening the pytree to size it — and reuses it across
    steps."""
    tuner.register_profile("hint", 3, alpha_s=0.005, beta_s_per_b=1e-9)
    gen = _make_flat(3, group="hint", tune=True)
    reds = next(gen)
    v = [np.zeros(4096, np.float32) for _ in range(3)]
    _all(reds, lambda g: g.reduce(v[g.rank], op="sum"))
    for g in reds:
        assert g._payload_hint == 4096 * 4
    gen.close()


def test_tuner_knob_gates_probing():
    """Config.collective_tuner=False disables in-situ probing even on
    tune-flagged rings (the static crossover keeps working); the
    collective_tuner_probe_bytes / collective_tuner_min_chunk_bytes
    knobs bound the probe payload and the chunk floor."""
    from ray_tpu.config import get_config
    cfg = get_config()
    saved = cfg.collective_tuner
    cfg.collective_tuner = False
    try:
        gen = _make_flat(3, group="gated", tune=True)
        reds = next(gen)
        v = [np.ones(256, np.float32)] * 3
        _all(reds, lambda g: g.reduce(v[g.rank], op="sum"))
        gen.close()
        assert tuner.profile_for("gated", 3) is None
    finally:
        cfg.collective_tuner = saved
    assert cfg.collective_tuner_probe_bytes >= 64 * 1024
    assert cfg.collective_tuner_min_chunk_bytes >= 4096


# --- dag impl resolution --------------------------------------------------


def test_resolve_impl_hier_and_tuner_consultation():
    """_resolve_impl: explicit "hier" needs a real two-level placement
    (degrades to ring otherwise); with a tuned default profile the
    payload hint consults the tuner's bands; without one the static
    crossover still decides (the pre-tuner contract, kept verbatim)."""
    from ray_tpu.dag import MethodNode, _resolve_impl, allreduce

    def g(**kw):
        base = {"size": 4, "quantize": None, "impl": None,
                "payload_bytes": None}
        base.update(kw)
        return base

    assert _resolve_impl(g(impl="hier"), hier_ok=True) == "hier"
    assert _resolve_impl(g(impl="hier"), hier_ok=False) == "ring"
    assert _resolve_impl(g(), hier_ok=True) == "hier"  # N>2 multi-node
    assert _resolve_impl(g(size=2), hier_ok=True) == "star"
    # quantized + multi-node + big payload under a tuned profile:
    # codec rides the hierarchical cross-node leg
    tuner.register_profile("", 4, alpha_s=0.01, beta_s_per_b=1e-9,
                           hierarchical=True)
    s_h = tuner.hier_crossover(4, 0.01, 1e-9)
    assert _resolve_impl(g(quantize="int8", payload_bytes=2 * s_h),
                         hier_ok=True) == "hier"
    assert _resolve_impl(g(payload_bytes=2 * s_h),
                         hier_ok=True) == "hier"
    s_star = tuner.star_crossover(4, 0.01, 1e-9)
    assert _resolve_impl(g(payload_bytes=s_star // 2)) == "star"
    tuner.invalidate()
    # binding surface accepts the new impl
    nodes = [MethodNode(None, "m", ()), MethodNode(None, "m", ())]
    assert allreduce(nodes, impl="hier")[0].group["impl"] == "hier"
    with pytest.raises(ValueError, match="impl"):
        allreduce(nodes, impl="rings")


# --- dag compile wiring ---------------------------------------------------


def test_dag_build_hier_group_wiring():
    """CompiledDag._build_hier_group: co-located members get intra
    edges among themselves, first-of-node leaders get the inter ring,
    codec options land on the INTER sub-spec only."""
    from ray_tpu.dag import CompiledDag
    cd = CompiledDag.__new__(CompiledDag)
    cd._coll_timeout = 60.0
    cd._coll_spec = {}
    edges = []

    def fake_edge(p, c):
        edges.append((p, c))
        return {"edge": (p, c)}

    cd._new_edge = fake_edge
    g = {"id": "f" * 16, "op": "sum", "quantize": "int8",
         "chunk_bytes": None}
    idxs = [10, 11, 12, 13]                 # actor indices, world order
    by_node = {"A": [0, 1], "B": [2, 3]}    # member positions per node
    cd._build_hier_group(g, idxs, by_node)
    lead_a, mem_a = cd._coll_spec[10], cd._coll_spec[11]
    lead_b, mem_b = cd._coll_spec[12], cd._coll_spec[13]
    for s in (lead_a, mem_a, lead_b, mem_b):
        assert s["role"] == "hier" and s["nodes"] == [2, 2]
        assert s["intra"]["level"] == "intra"
        # codec confined to the cross-node leg
        assert "quantize" not in s["intra"]
    assert lead_a["inter"]["level"] == "inter"
    assert lead_a["inter"]["quantize"] == "int8"
    assert mem_a["inter"] is None and mem_b["inter"] is None
    # intra edges stay within a node's actors; inter connects leaders
    assert (10, 11) in edges and (11, 10) in edges
    assert (12, 13) in edges and (13, 12) in edges
    assert (10, 12) in edges and (12, 10) in edges
    assert lead_a["inter"]["from_prev"] == lead_b["inter"]["to_next"]
    assert lead_a["intra"]["group"] != lead_b["intra"]["group"]
    assert lead_a["group"] == g["id"][:12]


# --- train-plane e2e over a real 2-node cluster ---------------------------


def test_train_hier_gradient_sync_e2e_two_nodes(tmp_path):
    """End-to-end: a 2-node x 2-worker group gets the ring-of-rings
    wired by the controller (lazy-shm intra, TCP leader ring),
    train.allreduce_gradients — plain and bucketed — reduces exactly
    over it, and shard_bounds follows the nested hier split."""
    import ray_tpu
    from ray_tpu import train
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.config import Config
    from ray_tpu.train.api import ScalingConfig

    cfg = Config.from_env(num_workers_prestart=0,
                          default_max_task_retries=0)
    c = Cluster(config=cfg)
    c.add_node(num_cpus=2)
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.address, num_cpus=0, config=cfg)
    try:
        def train_fn():
            import numpy as _np

            from ray_tpu import train as _train
            ctx = _train.get_context()
            r = ctx.get_world_rank()
            # payload well past the tuned-chunk floor: the tuner's
            # agreed profile (not each rank's private timings) must
            # drive the chunking or the ring desyncs mid-phase
            g = {"w": _np.full(200_000, float(r + 1), _np.float32),
                 "b": _np.arange(8, dtype=_np.float32) * (r + 1)}
            out = _train.allreduce_gradients(g, op="mean")
            bout = _train.allreduce_gradients(g, op="mean",
                                              bucket_bytes=8192)
            spec = ctx._grad_sync or {}
            lo, hi = ctx.shard_bounds(4104)
            _train.report({
                "rank": r, "w0": float(out["w"][0]),
                "b3": float(out["b"][3]),
                "bw0": float(bout["w"][0]),
                "role": spec.get("role"), "nodes": spec.get("nodes"),
                "own": [int(lo), int(hi)]})

        res = train.JaxTrainer(
            train_fn,
            scaling_config=ScalingConfig(num_workers=4)).fit()
        assert res.error is None
        m = res.metrics
        assert m["w0"] == 2.5                  # mean of 1..4
        assert m["b3"] == 3.0 * 2.5
        assert m["bw0"] == m["w0"]             # bucketed == plain
        assert m["role"] == "hier" and sorted(m["nodes"]) == [2, 2]
        from ray_tpu.dag.ring import hier_seg_bounds
        assert tuple(m["own"]) == hier_seg_bounds(4104, m["nodes"], 0)
    finally:
        ray_tpu.shutdown()
        c.shutdown()
