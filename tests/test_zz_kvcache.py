"""Paged KV cache (llm/kvcache.py): block alloc/free/refcount, prefix
reuse, COW divergence, LRU eviction under pool pressure — and the two
parity contracts the subsystem is pinned to: the paged engine
bitwise-matches the monolithic cache on cache-cold requests, and a
prefix-cache-hit request's logits bitwise-match a cold request's.

(Late-alphabet name keeps the tier-1 870 s cutoff stable.)
"""

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.llm import kvcache as kc
from ray_tpu.llm import model as lm
from ray_tpu.llm.engine import LLMEngine
from ray_tpu.models import llama


@pytest.fixture(scope="module")
def tiny_model():
    cfg = llama.tiny(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, ffn_dim=128, dtype="float32",
                     logits_dtype="float32", attn_impl="reference")
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _prompt(seed, n):
    return [int(x) for x in
            np.random.default_rng(seed).integers(1, 127, n)]


# --- host-side block manager (no jax) ---------------------------------


def test_alloc_free_refcount():
    m = kc.KVBlockManager(20, 8, table_width=8)
    a = m.alloc_seq("a", _prompt(0, 20), 12)     # 32 tokens -> 4 blocks
    assert a["hit_tokens"] == 0
    assert len(a["new_blocks"]) == 4
    assert m.used_blocks() == 4 and m.free_blocks() == 15
    # trash (0) is never allocated
    assert kc.TRASH not in a["new_blocks"]
    # tail of the table is trash
    assert list(a["table"][4:]) == [kc.TRASH] * 4
    m.free_seq("a")     # no token stream: prompt-hash chain caches
    assert m.used_blocks() == 0
    # prompt had 2 FULL blocks (20 tokens at block 8) -> 2 cached;
    # the partial tail + horizon blocks went back to the free list
    assert m.cached_blocks() == 2
    assert m.free_blocks() == 17


def test_prefix_hit_refcounts_and_cap():
    m = kc.KVBlockManager(32, 8, table_width=8)
    toks = _prompt(1, 24)
    a = m.alloc_seq("a", toks, 8)
    m.free_seq("a", toks + [5] * 8)   # full stream: 4 full blocks cached
    assert m.cached_blocks() == 4
    # same prompt: hits are capped one token short of the prompt, so
    # a 24-token prompt hits 2 full blocks (16 tokens), never 3
    b = m.alloc_seq("b", toks, 8)
    assert b["hit_tokens"] == 16
    # shared blocks are ref-counted: still cached, now also in use
    assert m.used_blocks() == len(set(
        p for p in b["table"] if p != kc.TRASH))
    # a longer prompt extending the cached stream hits 3 blocks
    c = m.alloc_seq("c", toks + [5] * 8, 8)
    assert c["hit_tokens"] == 24
    m.free_seq("b")
    m.free_seq("c")
    assert m.used_blocks() == 0


def test_divergent_prompt_misses_after_shared_prefix():
    m = kc.KVBlockManager(32, 8, table_width=8)
    toks = _prompt(2, 32)
    m.alloc_seq("a", toks, 8)
    m.free_seq("a", toks)
    div = toks[:16] + [99] * 16       # diverges at block 2
    d = m.alloc_seq("d", div, 8)
    assert d["hit_tokens"] == 16      # only the shared blocks hit
    m.free_seq("d", div)
    # both chains now cached; the divergent suffix got its own blocks
    assert m.cached_blocks() >= 4


def test_cow_on_fork_divergence():
    m = kc.KVBlockManager(20, 8, table_width=8)
    toks = _prompt(3, 20)
    a = m.alloc_seq("a", toks, 12)
    table_a = list(m.seqs["a"].table)
    m.fork_seq("a", "b")
    # every block is now shared: writing any of them must COW
    got = m.ensure_writable("b", 2)
    assert got is not None
    old, new = got
    assert old == table_a[2] and new != old
    assert m.seqs["b"].table[2] == new
    assert m.seqs["a"].table[2] == old
    # the un-forked block of "a" is still exclusively referenced...
    m.free_seq("b")
    # ...so after the fork dies, "a"'s blocks are private again
    assert m.ensure_writable("a", 2) is None


def test_cow_protects_cached_blocks():
    """A block held by the prefix index must COW even at refcount 1 —
    writing it in place would silently corrupt the cached content
    behind its chain hash."""
    m = kc.KVBlockManager(20, 8, table_width=8)
    toks = _prompt(4, 16)
    m.alloc_seq("a", toks, 8)
    m.free_seq("a", toks)             # 2 blocks cached
    b = m.alloc_seq("b", toks, 8)
    assert b["hit_tokens"] == 8       # capped at n-1 -> 1 block
    assert m.ensure_writable("b", 0) is not None   # shared+cached: COW
    m.free_seq("b")


def test_lru_eviction_leaf_first_under_pressure():
    m = kc.KVBlockManager(9, 8, table_width=8)    # 8 usable blocks
    t1 = _prompt(5, 16)
    m.alloc_seq("a", t1, 0 or 8)
    m.free_seq("a", t1)               # chain1: 2 cached blocks
    t2 = _prompt(6, 16)
    m.alloc_seq("b", t2, 8)
    m.free_seq("b", t2)               # chain2: 2 cached blocks
    assert m.cached_blocks() == 4 and m.free_blocks() == 4
    # touch BOTH of chain1's blocks (the one-token tail lets the
    # lookup cap walk the full chain) so chain2 is the LRU victim
    hit, _ = m.lookup(t1 + [1])
    assert hit == 16
    # allocating 6 blocks forces eviction of 2: chain2's leaf FIRST,
    # then its root
    c = m.alloc_seq("c", _prompt(7, 40), 8)       # 48 tokens -> 6 blocks
    assert c is not None
    assert m.evicted_total == 2
    # chain1 survived (it was fresher)
    hit1, _ = m.lookup(t1 + [1])
    assert hit1 == 16
    hit2, _ = m.lookup(t2 + [1])
    assert hit2 == 0


def test_eviction_never_reclaims_pinned_hit_blocks():
    """Regression: alloc_seq pins its prefix-hit blocks BEFORE
    evicting for the remainder — an evicted-then-reallocated hit
    block would land in the table twice (prefix view + fresh write
    target) and silently corrupt the KV. When pinning makes the
    request unfittable, the alloc parks (None) instead."""
    m = kc.KVBlockManager(9, 8, table_width=8)    # 8 usable
    other = m.alloc_seq("c", _prompt(11, 28), 2)  # live: 4 blocks
    assert other is not None
    toks = _prompt(12, 24)
    m.alloc_seq("a", toks, 8)                     # remaining 4 blocks
    m.free_seq("a", toks + [7] * 8)               # 4 cached, 0 free
    assert m.cached_blocks() == 4 and m.free_blocks() == 0
    # b hits 2 blocks and needs 3 more; only the 2 non-hit cached
    # blocks are evictable once the hits are pinned -> park, and the
    # hit blocks' refcounts roll back
    b = m.alloc_seq("b", toks, 16)
    assert b is None
    assert m.used_blocks() == 4                   # only "c" holds refs
    # after the live seq frees, the same alloc succeeds with the hit
    # blocks intact (still cached) and no duplicates in the table
    m.free_seq("c")
    b = m.alloc_seq("b", toks, 16)
    assert b is not None and b["hit_tokens"] == 16
    live = [p for p in b["table"] if p != kc.TRASH]
    assert len(live) == len(set(live)), f"duplicate phys: {live}"
    m.free_seq("b")


def test_failed_admit_never_poisons_prefix_cache():
    """Regression: a request whose KV was never written (admit failed
    before the prefill scatter) must not index its zero/stale blocks
    under the prompt's chain hashes — free_seq(cache=False)."""
    m = kc.KVBlockManager(20, 8, table_width=8)
    toks = _prompt(13, 24)
    m.alloc_seq("dead", toks, 8)
    m.free_seq("dead", toks, cache=False)         # the engine's
    # kv_written=False path: nothing cached, everything freed
    assert m.cached_blocks() == 0
    assert m.free_blocks() == 19
    hit, _ = m.lookup(toks)
    assert hit == 0


def test_pool_exhausted_and_parked_alloc():
    m = kc.KVBlockManager(9, 8, table_width=16)
    # horizon wider than the whole pool: can NEVER fit
    with pytest.raises(kc.BlockPoolExhausted):
        m.alloc_seq("x", _prompt(8, 64), 40)
    # fits the pool but not right now (another seq holds the blocks):
    # alloc returns None (caller parks the admit) instead of raising
    m.alloc_seq("a", _prompt(9, 40), 8)           # 6 of 8 blocks
    assert m.alloc_seq("b", _prompt(10, 24), 8) is None
    m.free_seq("a")
    assert m.alloc_seq("b", _prompt(10, 24), 8) is not None


def test_config_knobs_select_paged_mode(tiny_model, monkeypatch):
    """The Config surface (kvcache_block_size / kvcache_pool_blocks /
    kvcache_prefix_cache) drives engine construction when the kwargs
    are left at None."""
    from ray_tpu.config import get_config
    cfg_obj = get_config()
    monkeypatch.setattr(cfg_obj, "kvcache_block_size", 8)
    monkeypatch.setattr(cfg_obj, "kvcache_pool_blocks", 40)
    monkeypatch.setattr(cfg_obj, "kvcache_prefix_cache", False)
    cfg, params = tiny_model
    eng = LLMEngine(cfg, params, max_slots=2, max_len=64,
                    prefill_buckets=(16,), cache_dtype="float32")
    assert eng._paged and eng._block == 8
    assert eng._kv.num_blocks == 40
    assert not eng._kv.prefix_cache
    monkeypatch.setattr(cfg_obj, "kvcache_block_size", 0)
    eng2 = LLMEngine(cfg, params, max_slots=2, max_len=64,
                     prefill_buckets=(16,), cache_dtype="float32")
    assert not eng2._paged and eng2._cache is not None


# --- device parity ----------------------------------------------------


def test_paged_bitwise_matches_monolithic_cold(tiny_model):
    """Acceptance pin: on cache-cold requests the paged engine's
    greedy tokens are IDENTICAL to the monolithic engine's — the
    gathered block view is the same bytes in the same order, so every
    decode step samples the same token."""
    cfg, params = tiny_model
    prompts = [_prompt(20 + i, 5 + 3 * i) for i in range(5)]

    async def gen(paged):
        eng = LLMEngine(cfg, params, max_slots=2, max_len=64,
                        prefill_buckets=(16,), cache_dtype="float32",
                        kv_block_size=8 if paged else 0,
                        prefix_cache=False)
        outs = await asyncio.gather(*[
            eng.generate(p, max_new_tokens=10) for p in prompts])
        await eng.stop()
        return [o["tokens"] for o in outs]

    mono = asyncio.run(gen(False))
    paged = asyncio.run(gen(True))
    assert paged == mono


def test_paged_long_prompt_matches_monolithic(tiny_model):
    """Chunked prefill through the block pool (prompt > biggest
    bucket) reproduces the monolithic chunked path's tokens."""
    cfg, params = tiny_model
    prompt = _prompt(30, 200)

    async def gen(paged):
        eng = LLMEngine(cfg, params, max_slots=2, max_len=512,
                        prefill_buckets=(64,), cache_dtype="float32",
                        kv_block_size=16 if paged else 0,
                        prefix_cache=False)
        out = await eng.generate(prompt, max_new_tokens=12)
        await eng.stop()
        return out["tokens"]

    assert asyncio.run(gen(True)) == asyncio.run(gen(False))


def test_prefix_hit_logits_bitwise_parity(tiny_model):
    """The satellite pin: a prefix-cache-hit request's first-token
    LOGITS (and its whole greedy generation) bitwise-match a cold
    request's. Direct device-level check: suffix prefill over gathered
    cached blocks vs one cold full prefill."""
    cfg, params = tiny_model
    B, W = 8, 8
    pool = kc.init_pool(cfg, 24, B, jnp.float32)
    toks = _prompt(40, 24)
    # cold: one bucket-32 prefill
    logits_cold, kv = lm.prefill(
        params, jnp.asarray(lm.pad_prompt(toks, 32)), jnp.int32(24),
        cfg, 32)
    logits_cold = np.asarray(logits_cold)
    # seed the pool with the prefix's first 2 blocks (16 tokens), the
    # bytes a previous identical request would have scattered
    phys = np.asarray([3, 4, kc.TRASH, kc.TRASH], np.int32)
    pool = kc.scatter_bucket(pool, kv, jnp.asarray(phys), 4)
    # hit path: gather the table, prefill ONLY the suffix at offset 16
    table = np.full((W,), kc.TRASH, np.int32)
    table[0], table[1], table[2] = 3, 4, 5
    acc = kc.gather_table(pool, jnp.asarray(table), 64)
    logits_hit, acc = lm.prefill_chunk(
        params, jnp.asarray(lm.pad_prompt(toks[16:], 8)), jnp.int32(8),
        jnp.int32(16), acc, cfg)
    assert np.array_equal(np.asarray(logits_hit), logits_cold)
    # the suffix KV it computed is also bitwise what the cold prefill
    # produced — decode then attends identical bytes
    assert np.array_equal(np.asarray(acc["k"][:, 16:24]),
                          np.asarray(kv["k"][:, 16:24]))


def test_prefix_hit_generation_matches_cold_engine(tiny_model):
    """End-to-end through the engine: warm the prefix cache with one
    request, then a second request sharing the prefix must (a) report
    hit tokens, (b) generate exactly what a cold engine generates."""
    cfg, params = tiny_model
    shared = _prompt(50, 32)                  # 4 full blocks at B=8
    req = shared + _prompt(51, 10)            # shared prefix + suffix

    async def cold():
        eng = LLMEngine(cfg, params, max_slots=2, max_len=128,
                        prefill_buckets=(16, 64),
                        cache_dtype="float32", kv_block_size=8,
                        prefix_cache=False)
        out = await eng.generate(req, max_new_tokens=12)
        await eng.stop()
        return out

    async def warm():
        eng = LLMEngine(cfg, params, max_slots=2, max_len=128,
                        prefill_buckets=(16, 64),
                        cache_dtype="float32", kv_block_size=8,
                        prefix_cache=True)
        await eng.generate(shared, max_new_tokens=4)
        out = await eng.generate(req, max_new_tokens=12)
        stats = eng.stats
        await eng.stop()
        return out, stats

    cold_out = asyncio.run(cold())
    hit_out, stats = asyncio.run(warm())
    assert hit_out["prefix_hit_tokens"] >= 24, hit_out
    assert stats["prefix_hit_tokens"] >= 24
    assert hit_out["tokens"] == cold_out["tokens"]
    assert cold_out["prefix_hit_tokens"] == 0


def test_block_aligned_stream_never_caches_unwritten_tail(tiny_model):
    """Regression: each decode step writes the PREVIOUS token's KV, so
    the final sampled token's position is never written. A stream
    ending exactly on a block boundary must NOT cache that last block
    — a later request extending the stream would attend one
    stale/zero KV position and silently diverge from a cold engine."""
    cfg, params = tiny_model
    # prompt 24 + 8 generated = 32 tokens = exactly 4 blocks at B=8;
    # position 31 (the last token's KV) is never written
    warm_prompt = _prompt(80, 24)

    async def warmed():
        eng = LLMEngine(cfg, params, max_slots=2, max_len=128,
                        prefill_buckets=(16, 64),
                        cache_dtype="float32", kv_block_size=8,
                        prefix_cache=True)
        first = await eng.generate(warm_prompt, max_new_tokens=8)
        # follow-up turn: the full previous stream as prompt + more
        ext = warm_prompt + first["tokens"] + _prompt(81, 5)
        out = await eng.generate(ext, max_new_tokens=10)
        await eng.stop()
        return ext, out

    ext, hit_out = asyncio.run(warmed())
    # the hit must stop short of the unwritten final position: at most
    # 31 written tokens -> 3 full blocks = 24 hit tokens
    assert hit_out["prefix_hit_tokens"] <= 24, hit_out

    async def cold(prompt):
        eng = LLMEngine(cfg, params, max_slots=2, max_len=128,
                        prefill_buckets=(16, 64),
                        cache_dtype="float32", kv_block_size=8,
                        prefix_cache=False)
        out = await eng.generate(prompt, max_new_tokens=10)
        await eng.stop()
        return out

    cold_out = asyncio.run(cold(ext))
    assert hit_out["tokens"] == cold_out["tokens"]


def test_pool_pressure_parks_admits_and_evicts(tiny_model):
    """A pool smaller than the concurrent demand: admissions park
    (requests still ALL complete, in order of arrival), and cached
    chains are LRU-evicted to make room (llm_kv_blocks_evicted_total
    counts them)."""
    cfg, params = tiny_model
    # 2 slots, horizon 4 blocks per request, pool of 9 usable blocks:
    # two live requests fit, a third must wait for a free_seq
    eng = LLMEngine(cfg, params, max_slots=2, max_len=64,
                    prefill_buckets=(16,), cache_dtype="float32",
                    kv_block_size=8, kv_pool_blocks=10,
                    prefix_cache=True)

    async def go():
        outs = await asyncio.gather(*[
            eng.generate(_prompt(60 + i, 12), max_new_tokens=10)
            for i in range(6)])
        await eng.stop()
        return outs

    outs = asyncio.run(go())
    assert all(len(o["tokens"]) == 10 for o in outs)
    # finished chains were cached, then evicted under pressure
    assert eng._kv.evicted_total > 0
    assert eng._kv.used_blocks() == 0


def test_kv_accounting_gauges(tiny_model):
    """llm_kv_blocks_{used,cached} reflect the pool; the PR 11
    llm_kv_cache_bytes attribution now reports LIVE bytes (used +
    cached blocks), not the whole preallocated pool."""
    from ray_tpu.util import metrics as M
    cfg, params = tiny_model
    eng = LLMEngine(cfg, params, max_slots=2, max_len=64,
                    prefill_buckets=(16,), cache_dtype="float32",
                    kv_block_size=8)

    async def go():
        await eng.generate(_prompt(70, 12), max_new_tokens=8)
        await eng.stop()

    asyncio.run(go())
    reg = M._REGISTRY
    used = sum(reg["llm_kv_blocks_used"]._values.values())
    cached = sum(reg["llm_kv_blocks_cached"]._values.values())
    assert used == 0                      # request finished
    assert cached >= 1                    # its prompt chain is cached
    bb = kc.pool_block_bytes(eng._pool)
    kv_bytes = sum(reg["llm_kv_cache_bytes"]._values.values())
    assert kv_bytes == bb * cached


def test_copy_block_device_cow(tiny_model):
    """The COW divergence path at the device level: after copy_block,
    the clone holds the same bytes; writing the clone leaves the
    original untouched."""
    cfg, _ = tiny_model
    pool = kc.init_pool(cfg, 6, 8, jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1),
                          pool["k"][:, 1].shape)
    pool = {"k": pool["k"].at[:, 1].set(k), "v": pool["v"]}
    pool = kc.copy_block(pool, 1, 2)
    assert np.array_equal(np.asarray(pool["k"][:, 1]),
                          np.asarray(pool["k"][:, 2]))
    pool = {"k": pool["k"].at[:, 2, 0].add(1.0), "v": pool["v"]}
    assert not np.array_equal(np.asarray(pool["k"][:, 1]),
                              np.asarray(pool["k"][:, 2]))
