"""Paged-attention decode kernel (ops/pallas/paged_attention.py) and
its serving integration: kernel-vs-gather parity (allclose on random
values, BITWISE on integer constructions), COW-forked tables diverging
mid-decode, tensor-parallel paged engines, chunk-grid-aligned prefix
hits, and the paged_attn_impl / paged_attn_interpret Config knobs.

All kernel tests run interpret=True — tier-1 (JAX_PLATFORMS=cpu)
exercises the real table walk / masking / online-softmax logic through
the Pallas interpreter, not a shadow path.

(Late-alphabet name keeps the tier-1 870 s cutoff stable.)
"""

import asyncio
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.llm import kvcache as kc
from ray_tpu.llm.engine import LLMEngine
from ray_tpu.models import llama
from ray_tpu.ops.pallas import paged_attention as pa


@pytest.fixture(scope="module")
def tiny_model():
    cfg = llama.tiny(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, ffn_dim=128, dtype="float32",
                     logits_dtype="float32", attn_impl="reference")
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _prompt(seed, n):
    return [int(x) for x in
            np.random.default_rng(seed).integers(1, 127, n)]


def _ref_greedy(cfg, params, prompt, n):
    toks = list(prompt)
    for _ in range(n):
        logits = llama.forward(params, jnp.array([toks], jnp.int32),
                               cfg)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def _tp_mesh(size):
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()[:size]), ("tensor",))


def _rand_case(seed, *, b, w, bs, kvh, g, hd, nb):
    """Random q/pool + disjoint per-slot block tables."""
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, kvh, g, hd)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(nb, bs, kvh, hd))
                    .astype(np.float32))
    v = jnp.asarray(rng.normal(size=(nb, bs, kvh, hd))
                    .astype(np.float32))
    tables = jnp.asarray(
        (1 + np.arange(b * w)).reshape(b, w).astype(np.int32))
    return q, k, v, tables


# --- kernel unit (interpret mode) -------------------------------------


def test_kernel_matches_gather_reference_uneven_lengths():
    """Random values, uneven table lengths including a single-position
    slot and a max-len slot: the fused kernel agrees with the
    gather-then-softmax reference to f32 rounding."""
    b, w, bs, kvh, g, hd = 3, 4, 8, 2, 2, 16
    q, k, v, tables = _rand_case(0, b=b, w=w, bs=bs, kvh=kvh, g=g,
                                 hd=hd, nb=1 + b * w)
    lengths = jnp.asarray([1, 7, w * bs], jnp.int32)
    got = pa.paged_attention(q, k, v, tables, lengths, interpret=True)
    want = pa.paged_attention_reference(q, k, v, tables, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-6, atol=2e-6)


def test_kernel_under_jit_matches_eager():
    """The kernel composes with jax.jit (the shape it runs in inside
    paged_decode_steps' scan) without changing its output."""
    b, w, bs, kvh, g, hd = 2, 4, 8, 2, 2, 16
    q, k, v, tables = _rand_case(1, b=b, w=w, bs=bs, kvh=kvh, g=g,
                                 hd=hd, nb=1 + b * w)
    lengths = jnp.asarray([5, 20], jnp.int32)
    fn = jax.jit(functools.partial(pa.paged_attention, interpret=True))
    eager = pa.paged_attention(q, k, v, tables, lengths,
                               interpret=True)
    jitted = fn(q, k, v, tables, lengths)
    assert np.array_equal(np.asarray(eager), np.asarray(jitted))


def test_kernel_bitwise_on_integer_pow2_construction():
    """BITWISE kernel-vs-gather parity on a construction where both
    summation orders are exact: constant K makes every score equal
    (softmax weights are exactly 1/count), integer-valued V makes the
    weighted sums exact, and POWER-OF-TWO valid lengths make 1/count
    exactly representable. (The gather path divides by the softmax sum
    BEFORE accumulating, the online-softmax kernel divides AFTER — the
    two orders only agree bitwise when 1/count is exact, which is why
    the lengths here are 1/4/16/32, not arbitrary.)"""
    b, w, bs, kvh, g, hd = 4, 4, 8, 2, 2, 16
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(b, kvh, g, hd))
                    .astype(np.float32))
    nb = 1 + b * w
    k = jnp.ones((nb, bs, kvh, hd), jnp.float32)
    v = jnp.asarray(rng.integers(-8, 8, size=(nb, bs, kvh, hd))
                    .astype(np.float32))
    tables = jnp.asarray(
        (1 + np.arange(b * w)).reshape(b, w).astype(np.int32))
    lengths = jnp.asarray([1, 4, 16, 32], jnp.int32)   # powers of two
    got = np.asarray(
        pa.paged_attention(q, k, v, tables, lengths, interpret=True))
    want = np.asarray(
        pa.paged_attention_reference(q, k, v, tables, lengths))
    assert np.array_equal(got, want)


def test_kernel_cow_forked_tables_diverge_mid_decode():
    """Two slots share every physical block (a fork); the fork then
    COWs its last block and writes a divergent KV entry. The parent's
    attention output must be bitwise-unchanged, the fork's must follow
    its private block — the kernel reads through the TABLES, not
    through any per-slot copy."""
    b, w, bs, kvh, g, hd = 2, 4, 8, 2, 2, 16
    nb = 8
    rng = np.random.default_rng(3)
    # identical query on both slots: while the tables are fully shared
    # the two rows must come out bitwise-identical
    q = jnp.asarray(np.broadcast_to(
        rng.normal(size=(1, kvh, g, hd)).astype(np.float32),
        (b, kvh, g, hd)).copy())
    k = rng.normal(size=(nb, bs, kvh, hd)).astype(np.float32)
    v = rng.normal(size=(nb, bs, kvh, hd)).astype(np.float32)
    shared = np.asarray([[1, 2, 3, kc.TRASH]] * 2, np.int32)
    length = 20                                 # pos 19 in block 3
    lengths = jnp.asarray([length, length], jnp.int32)
    before = np.asarray(pa.paged_attention(
        q, jnp.asarray(k), jnp.asarray(v), jnp.asarray(shared),
        lengths, interpret=True))
    assert np.array_equal(before[0], before[1])

    # COW: clone phys 3 -> 4, repoint the fork, diverge position 19
    k[4], v[4] = k[3], v[3]
    k[4, 19 % bs] += 1.0
    v[4, 19 % bs] -= 1.0
    forked = shared.copy()
    forked[1, 2] = 4
    after = np.asarray(pa.paged_attention(
        q, jnp.asarray(k), jnp.asarray(v), jnp.asarray(forked),
        lengths, interpret=True))
    assert np.array_equal(after[0], before[0])          # parent intact
    assert not np.array_equal(after[1], before[1])      # fork diverged
    want = np.asarray(pa.paged_attention_reference(
        q, jnp.asarray(k), jnp.asarray(v), jnp.asarray(forked),
        lengths))
    np.testing.assert_allclose(after, want, rtol=2e-6, atol=2e-6)


# --- impl resolution + Config knobs -----------------------------------


def test_resolve_attn_impl():
    # auto resolves by backend: gather on the CPU tier-1 backend
    assert kc.resolve_attn_impl("auto") == "gather"
    assert kc.resolve_attn_impl("gather") == "gather"
    assert kc.resolve_attn_impl("paged_flash") == "paged_flash"
    with pytest.raises(ValueError, match="auto|paged_flash|gather"):
        kc.resolve_attn_impl("flash")


def test_config_knobs_drive_engine_impl(tiny_model, monkeypatch):
    """paged_attn_impl / paged_attn_interpret (Config, overridable via
    RAY_TPU_PAGED_ATTN_IMPL / RAY_TPU_PAGED_ATTN_INTERPRET) select the
    decode attention path when the kv_impl kwarg is left at None; off
    TPU the engine force-enables the interpreter for the kernel impl."""
    from ray_tpu.config import get_config
    cfg_obj = get_config()
    cfg, params = tiny_model
    kw = dict(max_slots=2, max_len=32, prefill_buckets=(8,),
              cache_dtype="float32", kv_block_size=8)

    monkeypatch.setattr(cfg_obj, "paged_attn_impl", "gather")
    eng = LLMEngine(cfg, params, **kw)
    assert eng._paged and eng._kv_impl == "gather"
    assert not eng._kv_interpret
    assert eng.stats["kv_impl"] == "gather"

    monkeypatch.setattr(cfg_obj, "paged_attn_impl", "paged_flash")
    monkeypatch.setattr(cfg_obj, "paged_attn_interpret", False)
    eng = LLMEngine(cfg, params, **kw)
    assert eng._kv_impl == "paged_flash"
    assert eng._kv_interpret          # forced: no TPU backend here

    # the explicit kwarg beats the Config knob
    eng = LLMEngine(cfg, params, kv_impl="gather", **kw)
    assert eng._kv_impl == "gather"


# --- decode-path parity through the engine ----------------------------


def test_engine_kernel_impl_matches_gather_impl(tiny_model):
    """A/B the two decode attention impls through the full engine:
    same prompts, same greedy tokens — the fused kernel replaces the
    gathered view without moving a single sampled token. Also pins the
    new per-impl metrics: llm_paged_attn_steps_total tags the steps,
    llm_kv_gather_bytes_avoided_total counts only for the kernel."""
    from ray_tpu.util import metrics as M
    cfg, params = tiny_model
    prompts = [_prompt(100 + i, 5 + 3 * i) for i in range(3)]

    async def gen(impl):
        eng = LLMEngine(cfg, params, max_slots=2, max_len=32,
                        prefill_buckets=(8,), cache_dtype="float32",
                        kv_block_size=8, prefix_cache=False,
                        kv_impl=impl)
        outs = await asyncio.gather(*[
            eng.generate(p, max_new_tokens=8) for p in prompts])
        await eng.stop()
        return [o["tokens"] for o in outs]

    gather = asyncio.run(gen("gather"))
    reg = M._REGISTRY
    avoided0 = sum(
        reg["llm_kv_gather_bytes_avoided_total"]._values.values())
    flash = asyncio.run(gen("paged_flash"))
    assert flash == gather
    steps = reg["llm_paged_attn_steps_total"]._values
    assert any("paged_flash" in str(k) and v > 0
               for k, v in steps.items())
    assert any("gather" in str(k) and v > 0 for k, v in steps.items())
    avoided1 = sum(
        reg["llm_kv_gather_bytes_avoided_total"]._values.values())
    assert avoided1 > avoided0        # kernel runs count avoided bytes


# --- tensor-parallel paged engines ------------------------------------


def test_tp_engine_runs_paged_gather(tiny_model):
    """The TP restriction is lifted: a meshed engine with a block size
    runs PAGED (pool sharded on its kv-head dim, tables replicated)
    and reproduces the reference greedy tokens."""
    cfg, params = tiny_model
    prompts = [[3, 7, 11], [9, 1], [5, 5, 5, 5]]
    refs = [_ref_greedy(cfg, params, p, 8) for p in prompts]

    async def go():
        eng = LLMEngine(cfg, params, max_slots=2, max_len=32,
                        prefill_buckets=(8,), cache_dtype="float32",
                        kv_block_size=8, prefix_cache=False,
                        kv_impl="gather", mesh=_tp_mesh(2))
        assert eng._paged
        outs = await asyncio.gather(*[
            eng.generate(p, max_new_tokens=8) for p in prompts])
        await eng.stop()
        return outs

    outs = asyncio.run(go())
    for o, ref in zip(outs, refs):
        assert o["tokens"] == ref


def test_tp_engine_kernel_with_prefix_reuse(tiny_model):
    """Full acceptance row: tensor-parallel engine + fused kernel +
    prefix cache. The shard_mapped kernel (heads sharded, tables
    replicated) must reproduce reference tokens, and a shared-prefix
    request must land measurable hit tokens."""
    cfg, params = tiny_model
    shared = _prompt(110, 32)
    req = shared + _prompt(111, 6)
    ref = _ref_greedy(cfg, params, req, 8)

    async def go():
        eng = LLMEngine(cfg, params, max_slots=2, max_len=64,
                        prefill_buckets=(16, 64),
                        cache_dtype="float32", kv_block_size=8,
                        prefix_cache=True, kv_impl="paged_flash",
                        mesh=_tp_mesh(2))
        assert eng._paged and eng._kv_impl == "paged_flash"
        await eng.generate(shared, max_new_tokens=4)
        out = await eng.generate(req, max_new_tokens=8)
        stats = eng.stats
        await eng.stop()
        return out, stats

    out, stats = asyncio.run(go())
    assert out["prefix_hit_tokens"] >= 24, out
    assert stats["prefix_hit_tokens"] >= 24
    assert out["tokens"] == ref


# --- chunk-grid-aligned prefix hits -----------------------------------


def test_prefill_start_rounds_down_to_chunk_grid(tiny_model):
    """Unit: on a flash-capable chunked-prefill path the suffix start
    rounds DOWN to the chunk grid (bounded per-offset compiles); on
    the XLA reference path the hit is used as-is."""
    cfg, params = tiny_model          # attn_impl="reference"
    eng = LLMEngine(cfg, params, max_slots=1, max_len=64,
                    prefill_buckets=(16,), cache_dtype="float32",
                    kv_block_size=8)
    assert eng._prefill_start(0) == 0
    assert eng._prefill_start(24) == 24        # reference: exact hit

    fl_cfg = llama.tiny(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, ffn_dim=128, dtype="float32",
                        logits_dtype="float32",
                        attn_impl="flash_interpret")
    fl_params = llama.init_params(jax.random.PRNGKey(0), fl_cfg)
    eng_fl = LLMEngine(fl_cfg, fl_params, max_slots=1, max_len=512,
                       prefill_buckets=(128,), cache_dtype="float32",
                       kv_block_size=8)
    assert eng_fl._prefill_start(0) == 0
    assert eng_fl._prefill_start(8) == 0       # sub-chunk hit: recompute
    assert eng_fl._prefill_start(160) == 128   # rounds down to grid
    assert eng_fl._prefill_start(256) == 256   # already aligned


@pytest.mark.slow
def test_flash_prefix_hit_matches_cold_engine():
    """End-to-end on the flash chunked-prefill path: a prefix-hit
    request enters the compiled chunk-grid flash variants (start
    rounded down, < one chunk recomputed into trash-targeted blocks)
    and still generates exactly what a cold engine generates."""
    fl_cfg = llama.tiny(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, ffn_dim=128, dtype="float32",
                        logits_dtype="float32",
                        attn_impl="flash_interpret")
    params = llama.init_params(jax.random.PRNGKey(0), fl_cfg)
    shared = _prompt(120, 160)
    req = shared + _prompt(121, 10)

    async def gen(prefix_cache):
        eng = LLMEngine(fl_cfg, params, max_slots=2, max_len=512,
                        prefill_buckets=(128,), cache_dtype="float32",
                        kv_block_size=8, prefix_cache=prefix_cache)
        if prefix_cache:
            await eng.generate(shared, max_new_tokens=4)
        out = await eng.generate(req, max_new_tokens=8)
        await eng.stop()
        return out

    cold = asyncio.run(gen(False))
    warm = asyncio.run(gen(True))
    assert warm["prefix_hit_tokens"] >= 128, warm
    assert warm["tokens"] == cold["tokens"]
    assert cold["prefix_hit_tokens"] == 0
