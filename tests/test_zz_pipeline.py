"""Pipeline parallelism (train/pipeline.py + dag/runtime.py
pipe_exec_loop): schedule-order units, 2-stage numerical parity vs a
single-process reference, stage-death -> typed PeerLostError with a
flight-recorder path, the controller's pipeline reshape gate,
activation-ref no-leak via device_store accounting, observability
surfaces (pipe:stage<k> chrome lanes, trace_step pull-in, state
summary), the pipeline_* knob family, and a slow multi-process e2e on
a real cluster.

Named late-alphabet so the tier-1 870 s cutoff stays stable.
"""

import os
import threading
import time

import numpy as np
import pytest

from ray_tpu.train import pipeline as pl


# --- schedule-order units -------------------------------------------------


@pytest.mark.parametrize("S,M", [(2, 4), (3, 8), (4, 5), (4, 4), (1, 3)])
def test_1f1b_schedule_deps_and_memory(S, M):
    sched = pl.compile_schedule(S, M, "1f1b")
    sim = pl.simulate(sched)           # raises on a dependency deadlock
    # steady-state memory bound: stage p holds at most S-p in-flight
    # microbatch inputs — O(stages), NOT O(microbatches)
    for p in range(S):
        assert sim["in_flight"][p] <= S - p
        # every microbatch appears exactly once per direction
        fwd = [op[1] for op in sched[p] if op[0] == "F"]
        bwd = [op[1] for op in sched[p] if op[0] == "B"]
        assert sorted(fwd) == list(range(M))
        assert sorted(bwd) == list(range(M))
    # unit-cost simulation reproduces the analytic bubble exactly
    assert sim["bubble_fraction"] == pytest.approx(
        pl.bubble_fraction(S, M))


@pytest.mark.parametrize("S,M", [(2, 4), (3, 8)])
def test_gpipe_schedule_memory_is_m(S, M):
    sched = pl.compile_schedule(S, M, "gpipe")
    sim = pl.simulate(sched)
    assert all(f == M for f in sim["in_flight"])   # the O(M) contrast
    assert sim["bubble_fraction"] == pytest.approx(
        pl.bubble_fraction(S, M))


def test_fill_drain_counts():
    # S=3, M=4: 1F1B stage p warms up min(M, S-1-p) forwards, so the
    # first backward lands after warm+1 forwards and the drain after
    # the last forward mirrors it (steady state ends F-then-B)
    for p, want_warm in [(0, 2), (1, 1), (2, 0)]:
        ops = pl.compile_schedule(3, 4, "1f1b")[p]
        fill, drain = pl.fill_drain_counts(ops)
        assert fill == want_warm + 1
        assert drain == want_warm + 1
    fill, drain = pl.fill_drain_counts(pl.compile_schedule(3, 4,
                                                           "gpipe")[0])
    assert fill == 4 and drain == 4


def test_interleaved_schedule_is_valid_and_tighter():
    flat = pl.simulate(pl.compile_schedule(4, 4, "1f1b"))
    inter = pl.simulate(pl.compile_schedule(2, 4, "interleaved",
                                            virtual=2), virtual=2)
    # same virtual depth (4), fewer workers: the interleaved schedule
    # must stay dependency-valid and keep its bubble at or under the
    # flat 4-stage pipeline's
    assert inter["bubble_fraction"] <= flat["bubble_fraction"] + 1e-9


def test_schedule_validation_errors():
    with pytest.raises(ValueError):
        pl.compile_schedule(0, 4)
    with pytest.raises(ValueError):
        pl.compile_schedule(2, 0)
    with pytest.raises(ValueError):
        pl.compile_schedule(2, 4, "mpmd")
    with pytest.raises(ValueError):
        pl.compile_schedule(2, 4, "1f1b", virtual=2)


# --- in-process harness ---------------------------------------------------
#
# Stages run the REAL pinned loop (dag/runtime.py pipe_exec_loop) on
# threads over eagerly-created shm channels (pl.wire_local) — the same
# code path a cluster dag actor executes, without paying cluster spin-up
# inside tier-1.


def _linear_stages(dtype=np.float32, integer=False):
    import jax.numpy as jnp
    rng = np.random.default_rng(7)
    if integer:
        W0 = jnp.asarray(rng.integers(-2, 3, (8, 16)).astype(dtype))
        W1 = jnp.asarray(rng.integers(-2, 3, (16, 1)).astype(dtype))
    else:
        W0 = jnp.asarray(rng.standard_normal((8, 16)).astype(dtype) * .1)
        W1 = jnp.asarray(rng.standard_normal((16, 1)).astype(dtype) * .1)

    def stage0(params, xy):
        x, y = xy
        return (x @ params, y)

    def stage1(params, hy):
        h, y = hy
        return jnp.mean((h @ params - y) ** 2)
    return (stage0, W0), (stage1, W1)


def _microbatches(M, integer=False, batch=4):
    import jax.numpy as jnp
    rng = np.random.default_rng(3)
    out = []
    for _ in range(M):
        if integer:
            x = rng.integers(-2, 3, (batch, 8)).astype(np.float32)
            y = rng.integers(-2, 3, (batch, 1)).astype(np.float32)
        else:
            x = rng.standard_normal((batch, 8)).astype(np.float32)
            y = rng.standard_normal((batch, 1)).astype(np.float32)
        out.append((jnp.asarray(x), jnp.asarray(y)))
    return out


def _reference_params(stages, xs, steps, lr=0.5):
    """Single-process reference: grads of the composed model, summed
    over microbatches in feed order, divided by M, SGD — the exact
    computation the pipeline distributes."""
    import jax
    import optax
    (f0, W0), (f1, W1) = stages

    def full_loss(params, xy):
        return f1(params[1], f0(params[0], xy))
    opt = optax.sgd(lr)
    p, st = (W0, W1), None
    st = opt.init((W0, W1))
    for _ in range(steps):
        acc = None
        for mb in xs:
            g = jax.grad(full_loss)(p, mb)
            acc = g if acc is None else \
                jax.tree_util.tree_map(lambda a, b: a + b, acc, g)
        mean = jax.tree_util.tree_map(lambda a: a / len(xs), acc)
        upd, st = opt.update(mean, st, p)
        p = optax.apply_updates(p, upd)
    return p


def _run_pipeline(stages, xs, steps, *, schedule="1f1b", replicas=1,
                  device=False, lr=0.5, timeout_s=30.0, optimizer=None,
                  zero=None):
    from ray_tpu.dag.channel import DATA, STOP
    from ray_tpu.dag.runtime import pipe_exec_loop
    from ray_tpu.runtime.serialization import loads_oob, serialize
    import optax
    (f0, W0), (f1, W1) = stages
    M = len(xs)
    specs, inputs, res, chans = pl.wire_local(
        2, M, schedule=schedule, replicas=replicas, device=device,
        timeout_s=timeout_s)
    opt = optimizer or (lambda: optax.sgd(lr))
    actors = [
        [pl.PipelineStageActor(f0, W0, optimizer=opt(), zero=zero)
         for _ in range(replicas)],
        [pl.PipelineStageActor(f1, W1, optimizer=opt(), is_last=True,
                               zero=zero)
         for _ in range(replicas)]]
    threads = []
    for k in range(2):
        for j in range(replicas):
            t = threading.Thread(target=pipe_exec_loop,
                                 args=(actors[k][j], specs[k][j]),
                                 daemon=True)
            t.start()
            threads.append(t)
    losses = []
    err = None
    try:
        for _ in range(steps):
            for j in range(replicas):
                for mb in xs[j::replicas]:
                    inputs[j].write(serialize(mb), DATA, timeout=10)
            step_losses = []
            for k in range(2):
                for j in range(replicas):
                    kind, payload = res[k][j].read_bytes(timeout_s)
                    body = loads_oob(payload)
                    if kind != DATA:
                        raise body if isinstance(body, BaseException) \
                            else RuntimeError(str(body))
                    if body["result"].get("loss") is not None:
                        step_losses.append(body["result"]["loss"])
            losses.append(float(np.mean(step_losses)))
    finally:
        try:
            for j in range(replicas):
                inputs[j].write(b"", STOP, timeout=5)
            deadline = time.monotonic() + 15
            for k in range(2):
                for j in range(replicas):
                    while time.monotonic() < deadline:
                        kind, _ = res[k][j].read_bytes(
                            max(0.1, deadline - time.monotonic()))
                        if kind == STOP:
                            break
        except Exception:
            pass
        for t in threads:
            t.join(timeout=10)
        for c in chans:
            c.close()
            try:
                c.unlink()
            except Exception:
                pass
    return actors, losses


def test_two_stage_parity_float():
    stages = _linear_stages()
    xs = _microbatches(4)
    actors, losses = _run_pipeline(stages, xs, steps=3)
    ref = _reference_params(stages, xs, steps=3)
    np.testing.assert_allclose(np.asarray(actors[0][0].get_params()),
                               np.asarray(ref[0]), rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(actors[1][0].get_params()),
                               np.asarray(ref[1]), rtol=1e-6, atol=1e-7)
    assert losses[-1] < losses[0]      # it actually trains


def test_two_stage_parity_bitwise_exact_sums():
    """Integer-valued fp32 data: every matmul/accumulation is exact, so
    the pipeline's chained per-stage vjp must reproduce the composed
    single-process gradient BITWISE — and GPipe vs 1F1B (different
    backward accumulation order) must agree bitwise too."""
    stages = _linear_stages(integer=True)
    xs = _microbatches(4, integer=True)
    # one step at a power-of-two lr: every product/sum stays far under
    # 2^24, so fp32 arithmetic is exact and order-independent
    ref = _reference_params(stages, xs, steps=1, lr=0.125)
    for schedule in ("1f1b", "gpipe"):
        actors, _ = _run_pipeline(stages, xs, steps=1,
                                  schedule=schedule, lr=0.125)
        assert np.array_equal(np.asarray(actors[0][0].get_params()),
                              np.asarray(ref[0])), schedule
        assert np.array_equal(np.asarray(actors[1][0].get_params()),
                              np.asarray(ref[1])), schedule


def test_zero_composed_per_stage_ring():
    """replicas=2: microbatches round-robin across two chains and each
    stage's replica pair syncs through a per-stage ZeRO-1 ring
    (ShardedOptimizer over RingReducer) at step end — replicas stay
    bitwise identical (the allgather guarantee), and the result matches
    the single-chain run up to reduction-order rounding."""
    import optax
    stages = _linear_stages()
    xs = _microbatches(4)
    actors, losses = _run_pipeline(
        stages, xs, steps=3, replicas=2,
        optimizer=lambda: optax.adam(1e-2))
    for k in range(2):
        a = np.asarray(actors[k][0].get_params())
        b = np.asarray(actors[k][1].get_params())
        assert np.array_equal(a, b), f"stage {k} replicas diverged"
    assert losses[-1] < losses[0]
    # per-stage ring group ids derive from the pipeline group
    # (<gid>.z<k>) so trace_step's pgroup prefix pulls them in
    from ray_tpu.train.zero import ShardedOptimizer
    assert isinstance(actors[0][0]._opt, ShardedOptimizer)
    assert actors[0][0]._zero_spec["group"].endswith(".z0")
    assert actors[1][1]._zero_spec["group"].endswith(".z1")


def test_stage_user_error_propagates():
    """A stage whose compute raises ships the ORIGINAL error to the
    driver (not a timeout) and terminates the whole pipeline."""
    import jax.numpy as jnp
    (f0, W0), (_f1, W1) = _linear_stages()

    def bad_stage(params, hy):
        raise ValueError("injected stage failure")

    xs = _microbatches(2)
    with pytest.raises(ValueError, match="injected stage failure"):
        _run_pipeline(((f0, W0), (bad_stage, W1)), xs, steps=1,
                      timeout_s=15.0)


def test_stage_death_peer_lost_with_flight_path(tmp_path):
    """A dead peer (nobody ever writes the backward edge) surfaces as
    the typed train.PeerLostError within the pipeline step timeout,
    carrying the stage-side flight-recorder dump path — the same
    post-mortem contract the collective ring plane has."""
    from ray_tpu.config import Config, get_config, set_config
    from ray_tpu.dag.channel import DATA
    from ray_tpu.dag.runtime import pipe_exec_loop
    from ray_tpu.runtime.serialization import loads_oob, serialize
    from ray_tpu.train.collective import PeerLostError
    old = get_config()
    set_config(Config(collective_flight_dir=str(tmp_path)))
    try:
        (f0, W0), _ = _linear_stages()
        # stage 0 of a 2-stage pipeline, with NO stage 1 attached:
        # forwards drain into the unread fwd edge, the first backward
        # recv times out at pipeline_step_timeout_s semantics
        specs, inputs, res, chans = pl.wire_local(2, 2,
                                                  timeout_s=1.0)
        actor = pl.PipelineStageActor(f0, W0)
        t = threading.Thread(target=pipe_exec_loop,
                             args=(actor, specs[0][0]), daemon=True)
        t.start()
        try:
            for mb in _microbatches(2):
                inputs[0].write(serialize(mb), DATA, timeout=5)
            kind, payload = res[0][0].read_bytes(20)
            err = loads_oob(payload)
            assert kind != DATA
            assert isinstance(err, PeerLostError)
            assert err.flight_recorder_path
            assert os.path.exists(err.flight_recorder_path)
            assert "flight recorder" in str(err)
        finally:
            t.join(timeout=10)
            for c in chans:
                c.close()
                try:
                    c.unlink()
                except Exception:
                    pass
    finally:
        set_config(old)


def test_activation_refs_do_not_leak():
    """Device-path transport: after every step the producer's device
    store is back to its baseline — schedule-owned refs are freed as
    the consumer materializes them, so steady-state memory is
    O(in-flight microbatches), not O(steps)."""
    from ray_tpu.runtime.device_store import _store
    stages = _linear_stages()
    xs = _microbatches(4)
    store = _store()
    base = store.live_count()
    actors, losses = _run_pipeline(stages, xs, steps=4, device=True)
    assert store.live_count() == base
    assert store.live_bytes() == 0 or store.live_count() == base
    # the transport actually ran (activation bytes were metered)
    from ray_tpu.util import metrics as m
    assert sum(m._REGISTRY["pipeline_activation_bytes_total"]
               ._values.values()) > 0
    # parity holds through the ref transport
    ref = _reference_params(stages, xs, steps=4)
    np.testing.assert_allclose(np.asarray(actors[0][0].get_params()),
                               np.asarray(ref[0]), rtol=1e-6, atol=1e-7)


def test_device_ship_falls_back_whole_on_unwalkable_container():
    """An exotic container (defaultdict) anywhere in the payload falls
    the WHOLE payload back to host staging and frees any refs already
    parked — a partial ship would strand tensors nobody can free."""
    import collections

    import jax.numpy as jnp
    from ray_tpu.dag.runtime import _ship_device_tree
    from ray_tpu.runtime.device_store import _store
    store = _store()
    base = store.live_count()
    dd = collections.defaultdict(list)
    dd["h"] = jnp.ones((4,))
    payload = {"pre": jnp.ones((8,)), "weird": dd}
    out, nbytes = _ship_device_tree(payload, ttl_s=60.0)
    assert out is payload          # untouched: host staging handles it
    assert nbytes == 0
    assert store.live_count() == base   # the parked "pre" ref was freed


def test_activation_ref_ttl_bounds_leaks():
    """An abandoned ref (consumer died before resolving) expires at
    its TTL instead of pinning memory forever — the
    pipeline_activation_ttl_s backstop."""
    import jax.numpy as jnp
    from ray_tpu.runtime.device_store import _store, put_device
    store = _store()
    base = store.live_count()
    ref = put_device(jnp.ones((4, 4)), ttl_s=0.05)
    assert store.live_count() == base + 1
    time.sleep(0.1)
    assert store.live_count() == base
    with pytest.raises(KeyError):
        ref.resolve()


def test_stop_injection_unwedges_boundary_parked_stages():
    """A stage dead at a step BOUNDARY can't relay STOP (shm edges
    carry no death signal; survivors park on their first recv retry) —
    Pipeline.teardown injects STOP directly on inter-stage in-edges.
    This exercises that mechanic: stages 1..2 of a 3-stage pipeline
    with stage 0 never started, unwedged by injected STOPs."""
    from ray_tpu.dag.channel import STOP, attach_channel
    from ray_tpu.dag.runtime import pipe_exec_loop
    (f0, W0), _ = _linear_stages()
    specs, inputs, res, chans = pl.wire_local(3, 2, timeout_s=0.5)
    actors = [pl.PipelineStageActor(f0, W0) for _ in range(2)]
    threads = []
    for k in (1, 2):
        t = threading.Thread(target=pipe_exec_loop,
                             args=(actors[k - 1], specs[k][0]),
                             daemon=True)
        t.start()
        threads.append(t)
    time.sleep(0.8)     # both readers are now parked at the boundary
    assert all(t.is_alive() for t in threads)
    for k in (1, 2):    # the teardown injection path
        ch = attach_channel(specs[k][0]["fwd_in"], "producer",
                            timeout=2.0)
        ch.write(b"", STOP, timeout=1.0)
        ch.close()
    for t in threads:
        t.join(timeout=10)
    assert not any(t.is_alive() for t in threads)
    for c in chans:
        c.close()
        try:
            c.unlink()
        except Exception:
            pass


# --- elastic gating -------------------------------------------------------


def _controller(datasets=None):
    import cloudpickle  # noqa: F401 — TrainController pickles train_fn
    from ray_tpu.train.api import RunConfig, ScalingConfig
    from ray_tpu.train.controller import TrainController
    ctrl = TrainController(lambda: None,
                           ScalingConfig(num_workers=(1, 3)),
                           RunConfig(), datasets=datasets)
    ctrl._workers = [object(), object()]
    ctrl._last_mirrors = {0: {}, 1: {}}
    return ctrl


def test_plan_reshape_gates_off_for_pipeline_groups():
    """A pipeline-topology group must NOT re-form in place around a
    lost worker (each rank hosts a distinct stage's parameters) — the
    controller's _plan_reshape falls through to the checkpoint-restart
    path, mirroring the streaming_split dataset gate."""
    dead = [(1, RuntimeError("boom"))]
    pending = {0, 1}
    ctrl = _controller()
    assert ctrl._plan_reshape(dead, pending) is not None  # baseline ok
    ctrl._last_pipeline = {0: True}       # rank 0 reported a pipeline
    assert ctrl._plan_reshape(dead, pending) is None
    # and the flag resets per incarnation like the mirror inventory
    ctrl._last_pipeline = {}
    assert ctrl._plan_reshape(dead, pending) is not None


def test_worker_poll_reports_pipeline_flag():
    from ray_tpu.train.api import TrainContext
    from ray_tpu.train.worker import TrainWorker
    w = TrainWorker(rank=0, world_size=1)
    w.ctx = TrainContext(rank=0, world_size=1, local_rank=0,
                         node_rank=0, resume_checkpoint=None)
    assert w.poll()["pipeline"] is False
    w.ctx.register_pipeline("deadbeef1234")
    assert w.poll()["pipeline"] is True
    assert w.ctx.pipeline_group == "deadbeef1234"
    # only the registering group clears the flag (teardown of an old
    # pipeline can't unflag a newer one), and clearing hands elastic
    # reshape back to the group
    w.ctx.unregister_pipeline("somebodyelse")
    assert w.poll()["pipeline"] is True
    w.ctx.unregister_pipeline("deadbeef1234")
    assert w.poll()["pipeline"] is False


# --- observability surfaces ----------------------------------------------


def _synthetic_pipe_events(group="abcdef123456", step=0, node=""):
    t = time.time()
    evs = []
    for stage in range(2):
        for mb in range(2):
            for kk, kind in enumerate(("F", "B")):
                ts = t + stage * 0.01 + mb * 0.02 + kk * 0.1
                evs.append({"cat": "pipeline", "name": "op", "ph": "X",
                            "ts": ts, "dur": 0.005, "stage": stage,
                            "chain": 0, "mb": mb, "kind": kind,
                            "step": step, "group": group,
                            "wait_s": 0.001, "pid": 1, "node": node})
        evs.append({"cat": "pipeline", "name": "step", "ph": "X",
                    "ts": t, "dur": 0.2, "stage": stage, "chain": 0,
                    "step": step, "group": group, "bubble_s": 0.02,
                    "pid": 1, "node": node})
    return evs


def test_to_chrome_pipe_lanes_and_forward_flows():
    from ray_tpu.util import tracing
    evs = _synthetic_pipe_events()
    out = tracing.to_chrome(evs)
    lanes = {r["tid"] for r in out
             if str(r.get("tid", "")).startswith("pipe:stage")}
    assert lanes == {"pipe:stage0", "pipe:stage1"}
    names = {r["name"] for r in out if r.get("cat") == "pipeline"}
    assert {"F0", "B0", "F1", "B1", "step0"} <= names
    flows = [r for r in out if r.get("name") == "pipe"]
    # 2 mbs x (1 F edge + 1 B edge) = 4 edges = 8 s/f records
    assert len(flows) == 8
    # forward-only under clock correction: every finish ts >= its start
    by_id = {}
    for r in flows:
        by_id.setdefault(r["id"], {})[r["ph"]] = r
    for pair in by_id.values():
        assert pair["f"]["ts"] >= pair["s"]["ts"]


def test_to_chrome_pipe_flows_never_backwards_under_skew():
    """Synthetic cross-node skew larger than the hop gap: clock
    correction plus the producer-start -> consumer-end rule keeps every
    pipeline flow arrow pointing forward."""
    from ray_tpu.util import tracing
    evs = _synthetic_pipe_events(node="aa") \
        + _synthetic_pipe_events(group="feedfacef00d", node="bb")
    out = tracing.to_chrome(evs, clock_offsets={"aa": 0.0, "bb": 5.0})
    flows = [r for r in out if r.get("name") == "pipe"]
    by_id = {}
    for r in flows:
        by_id.setdefault(r["id"], {})[r["ph"]] = r
    assert by_id
    for pair in by_id.values():
        assert pair["f"]["ts"] >= pair["s"]["ts"]


def test_trace_step_pulls_pipeline_spans_by_group():
    """TrainContext.trace_step tags its root span with the pipeline
    group (pgroup); filter_trace then pulls the step's pipe spans into
    the waterfall — and NOT another pipeline's spans sharing the step
    index (the collective-rounds scoping rule)."""
    from ray_tpu.train.api import TrainContext, set_context
    from ray_tpu.util import events, tracing
    if not tracing.requests_enabled():
        pytest.skip("request tracing disabled in this environment")
    ctx = TrainContext(rank=0, world_size=1, local_rank=0, node_rank=0,
                       resume_checkpoint=None)
    ctx.register_pipeline("abcdef123456")
    set_context(ctx)
    events.clear()
    try:
        with ctx.trace_step() as tid:
            for e in _synthetic_pipe_events(group="abcdef123456",
                                            step=0):
                events.record(e.pop("cat"), e.pop("name"), **e)
            for e in _synthetic_pipe_events(group="feedfacef00d",
                                            step=0):
                events.record(e.pop("cat"), e.pop("name"), **e)
            # what Pipeline.step() does after a step completes: bump
            # the pipeline's own counter so the span tags pstep=0
            ctx.pipeline_step += 1
        evs = events.dump()
        got = tracing.filter_trace(evs, tid)
        groups = {e.get("group") for e in got
                  if e.get("cat") == "pipeline"}
        assert groups == {"abcdef123456"}
        # the step root itself is in the filtered set with the pgroup
        roots = [e for e in got if e.get("cat") == "request"]
        assert any(e.get("pgroup") == "abcdef123456" for e in roots)
    finally:
        set_context(None)
        events.clear()


def test_state_pipeline_summary():
    from ray_tpu.util import state
    evs = []
    for s in range(3):
        for e in _synthetic_pipe_events(step=s):
            evs.append(e)
    rows = state.pipeline_from_events(evs)
    assert len(rows) == 2                       # one per stage
    for row in rows:
        assert row["steps"] == 3
        assert row["mean_step_s"] == pytest.approx(0.2)
        assert row["mean_bubble_s"] == pytest.approx(0.02)
        assert row["bubble_fraction"] == pytest.approx(0.1)


# --- knob family ----------------------------------------------------------


def test_pipeline_knob_defaults_resolve_from_config():
    """Pipeline reads every pipeline_* knob through pipeline_defaults:
    pipeline_schedule, pipeline_device_transport,
    pipeline_activation_ttl_s, pipeline_step_timeout_s."""
    from ray_tpu.config import Config, get_config, set_config
    old = get_config()
    try:
        set_config(Config(pipeline_schedule="gpipe",
                          pipeline_device_transport=False,
                          pipeline_activation_ttl_s=7.5,
                          pipeline_step_timeout_s=11.0))
        d = pl.pipeline_defaults()
        assert d == {"schedule": "gpipe", "device": False,
                     "ttl_s": 7.5, "timeout_s": 11.0}
    finally:
        set_config(old)


def test_pipeline_metrics_registered():
    m = pl.pipeline_metrics()
    assert set(m) == {"stage_step", "bubble", "activation_bytes"}
    assert m["bubble"].name == "pipeline_bubble_s"
    assert m["stage_step"].name == "pipeline_stage_step_s"
    assert m["activation_bytes"].name == \
        "pipeline_activation_bytes_total"


# --- slow multi-process e2e ----------------------------------------------


@pytest.mark.slow
def test_pipeline_e2e_cluster():
    """Real cluster: two PipelineStageActor dag actors driven by the
    Pipeline handle through its own channel wiring (device-ref
    transport on), losses decrease, stage stats come back at
    teardown."""
    import jax.numpy as jnp
    import optax

    import ray_tpu
    from ray_tpu import train

    ray_tpu.init(num_cpus=8)
    try:
        rng = np.random.default_rng(0)
        W0 = rng.standard_normal((8, 16)).astype(np.float32) * 0.1
        W1 = rng.standard_normal((16, 1)).astype(np.float32) * 0.1

        def stage0(params, xy):
            x, y = xy
            return (jnp.tanh(x @ params), y)

        def stage1(params, hy):
            h, y = hy
            return jnp.mean((h @ params - y) ** 2)

        Stage = ray_tpu.remote(train.PipelineStageActor)
        s0 = Stage.remote(stage0, W0, optimizer=optax.sgd(0.2))
        s1 = Stage.remote(stage1, W1, optimizer=optax.sgd(0.2),
                          is_last=True)
        pipe = train.Pipeline([s0, s1], num_microbatches=4,
                              device=True, timeout_s=120.0)
        try:
            xs = [(rng.standard_normal((4, 8)).astype(np.float32),
                   rng.standard_normal((4, 1)).astype(np.float32))
                  for _ in range(4)]
            losses = []
            for _ in range(4):
                out = pipe.step(xs)
                assert out.loss is not None
                assert 0.0 <= out.bubble_fraction <= 1.0
                losses.append(out.loss)
            assert losses[-1] < losses[0]
        finally:
            pipe.teardown()
        assert pipe.stage_stats is not None
        stages = {r["stage"] for r in pipe.stage_stats}
        assert stages == {0, 1}
        assert all(r["steps"] == 4 for r in pipe.stage_stats)
    finally:
        ray_tpu.shutdown()
