"""End-to-end request tracing (util/tracing.py request layer, serve
proxy/handle/replica threading, llm engine spans, tail-based sampling,
`ray-tpu trace` surfaces): one W3C-style trace id follows a request
from the proxy's HTTP boundary through the handle, replica, engine
batch slots, and nested tasks. Late-alphabet module name keeps the
tier-1 870 s cutoff stable."""

import asyncio
import http.client
import json
import os
import time

import pytest

from ray_tpu.util import events, tracing


def _clean_events():
    events.clear()


# -- trace context: mint / parse / format ------------------------------------

def test_traceparent_mint_format_parse_roundtrip():
    ctx = tracing.mint_context()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    int(ctx.trace_id, 16), int(ctx.span_id, 16)   # valid hex
    wire = tracing.format_traceparent(ctx)
    assert wire == f"00-{ctx.trace_id}-{ctx.span_id}-01"
    back = tracing.parse_traceparent(wire)
    assert back == ctx
    assert back.trace_id == ctx.trace_id
    # ids are unique per mint
    assert tracing.mint_context().trace_id != ctx.trace_id


def test_parse_traceparent_rejects_malformed_and_zero_ids():
    for bad in (None, "", "junk", "00-abc-def-01",
                "00-" + "g" * 32 + "-" + "1" * 16 + "-01",   # not hex
                "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # zero trace
                "00-" + "1" * 32 + "-" + "0" * 16 + "-01",   # zero span
                "zz-" + "1" * 32 + "-" + "2" * 16 + "-01"):  # bad ver
        assert tracing.parse_traceparent(bad) is None, bad
    # case-insensitive per W3C: upper-case hex parses, lowered
    up = "00-" + "A" * 32 + "-" + "B" * 16 + "-01"
    ctx = tracing.parse_traceparent(up)
    assert ctx is not None and ctx.trace_id == "a" * 32


def test_context_bind_wire_and_trace_id():
    assert tracing.current_context() is None
    assert tracing.current_trace_id() == ""
    assert tracing.wire_context() is None
    ctx = tracing.mint_context()
    tok = tracing.set_request_context(ctx)
    try:
        assert tracing.current_context() == ctx
        assert tracing.current_trace_id() == ctx.trace_id
        assert tracing.parse_traceparent(tracing.wire_context()) == ctx
    finally:
        tracing.reset_request_context(tok)
    assert tracing.current_context() is None


# -- tail-based sampling -----------------------------------------------------

def test_sampling_keeps_errors_and_slow_always():
    tid = "f" * 32
    assert not tracing.sample_keep(tid, rate=0.0)
    assert tracing.sample_keep(tid, rate=0.0, error=True)
    assert tracing.sample_keep(tid, rate=0.0, slow=True)
    assert tracing.sample_keep(tid, rate=1.0)


def test_sampling_is_deterministic_on_the_trace_id():
    # the low 8 hex digits decide: ...00000000 hashes to fraction 0
    # (kept at any rate > 0), ...ffffffff to ~1 (dropped below 1.0)
    low = "a" * 24 + "0" * 8
    high = "a" * 24 + "f" * 8
    assert tracing.sample_keep(low, rate=0.01)
    assert not tracing.sample_keep(high, rate=0.99)
    for tid in (low, high, tracing.mint_context().trace_id):
        first = tracing.sample_keep(tid, rate=0.5)
        assert all(tracing.sample_keep(tid, rate=0.5) == first
                   for _ in range(5))


def test_finish_request_roots_only_sampled_traces():
    """The tail decision gates the ROOT span: at trace_sample_rate=0 a
    healthy trace records nothing (it never surfaces) while an errored
    one records the root that makes it visible (traces_from_events)."""
    from ray_tpu.config import Config, set_config
    from ray_tpu.util.state import summarize_traces, traces_from_events
    _clean_events()
    try:
        set_config(Config.from_env(trace_sample_rate=0.0,
                                   trace_slow_threshold_s=60.0))
        t0 = time.time() - 0.01
        healthy = tracing.mint_context()
        assert tracing.finish_request(healthy, t0, time.time(),
                                      status="ok") is False
        errored = tracing.mint_context()
        assert tracing.finish_request(errored, t0, time.time(),
                                      status="error", error=True)
        deadline = tracing.mint_context()
        assert tracing.finish_request(deadline, t0, time.time(),
                                      status="deadline")
        # slow-over-threshold is kept even when healthy
        set_config(Config.from_env(trace_sample_rate=0.0,
                                   trace_slow_threshold_s=0.001))
        slow = tracing.mint_context()
        assert tracing.finish_request(slow, time.time() - 1.0,
                                      time.time(), status="ok")
    finally:
        set_config(Config.from_env())
    rows = traces_from_events(events.dump())
    ids = {r["trace_id"] for r in rows}
    assert healthy.trace_id not in ids
    assert {errored.trace_id, deadline.trace_id, slow.trace_id} <= ids
    by_id = {r["trace_id"]: r for r in rows}
    assert by_id[errored.trace_id]["status"] == "error"
    assert by_id[errored.trace_id]["error"]
    assert by_id[deadline.trace_id]["status"] == "deadline"
    assert by_id[slow.trace_id]["keep"] == "slow"
    s = summarize_traces(rows)
    assert s["traces"] == len(rows) and s["errors"] >= 2
    # errors sort before the (slower) healthy-slow trace
    assert rows[0]["error"]


# -- span recording + category budget ----------------------------------------

def test_request_spans_record_and_filter_by_trace():
    _clean_events()
    ctx = tracing.mint_context()
    other = tracing.mint_context()
    t0 = time.time()
    sid = tracing.record_request_span("proxy", "handler", ctx,
                                      ctx.span_id, t0, t0 + 0.01,
                                      deployment="d")
    tracing.record_request_span("replica", "handler", ctx, sid,
                                t0 + 0.002, t0 + 0.008)
    tracing.record_request_span("proxy", "handler", other,
                                other.span_id, t0, t0 + 0.5)
    tracing.record_batch_span("engine", "decode",
                              [ctx.trace_id], t0, t0 + 0.004, block=8)
    mine = tracing.filter_trace(events.dump(), ctx.trace_id)
    comps = {(e.get("component"), e.get("name")) for e in mine}
    assert ("proxy", "span") in comps
    assert ("replica", "span") in comps
    assert ("engine", "batch") in comps            # via links
    assert not any(e.get("trace") == other.trace_id for e in mine)


def test_filter_trace_pulls_step_tagged_collective_rounds():
    """A train-step trace references its collective rounds through the
    collective_step tag (TrainContext.collective_step -> ring spans).
    A step span carrying its ring GROUP id matches only that group's
    rounds (incl. hierarchical `<group>.n<i>`/`<group>.x` sub-rings) —
    another job sharing the step index must not cross-wire in."""
    _clean_events()
    ctx = tracing.mint_context()
    t0 = time.time()
    tracing.record_request_span("train", "train_step", ctx, "",
                                t0, t0 + 1.0, step=7, group="ga")
    for grp in ("ga", "ga.n0", "ga.x", "gb"):
        events.record("collective", "round", kind="allreduce", step=7,
                      rank=0, size=2, ts=t0 + 0.1, dur=0.05, group=grp)
    events.record("collective", "round", kind="allreduce", step=8,
                  rank=0, size=2, ts=t0 + 0.9, dur=0.05, group="ga")
    mine = tracing.filter_trace(events.dump(), ctx.trace_id)
    rounds = [(e.get("step"), e.get("group")) for e in mine
              if e.get("cat") == "collective"]
    assert sorted(rounds) == [(7, "ga"), (7, "ga.n0"), (7, "ga.x")]
    # a group-LESS step span falls back to step-only matching
    _clean_events()
    ctx2 = tracing.mint_context()
    tracing.record_request_span("train", "train_step", ctx2, "",
                                t0, t0 + 1.0, step=7)
    events.record("collective", "round", kind="allreduce", step=7,
                  rank=0, size=2, ts=t0 + 0.1, dur=0.05, group="gb")
    mine = tracing.filter_trace(events.dump(), ctx2.trace_id)
    assert any(e.get("cat") == "collective" for e in mine)


def test_trace_step_roots_once_and_nests_as_child_spans():
    """Only the OUTERMOST trace_step roots the trace; a nested one (or
    one opened inside a traced request) records a child span with its
    own id parented to the outer span — no duplicate roots, no span-id
    collision."""
    from ray_tpu.train.api import TrainContext
    _clean_events()
    tctx = TrainContext(0, 1, 0, 0, None)
    tctx.collective_step = 3
    with tctx.trace_step("step") as outer_tid:
        with tctx.trace_step("forward") as inner_tid:
            pass
    assert inner_tid == outer_tid            # one trace
    spans = [e for e in events.dump() if e.get("cat") == "request"
             and e.get("trace") == outer_tid]
    roots = [e for e in spans if e.get("root")]
    assert len(spans) == 2 and len(roots) == 1
    outer = roots[0]
    inner = next(e for e in spans if not e.get("root"))
    assert outer["seg"] == "step" and inner["seg"] == "forward"
    assert inner["span"] != outer["span"]
    assert inner["parent"] == outer["span"]
    assert outer["step"] == 3 and inner["step"] == 3
    _clean_events()


def test_request_category_cannot_evict_task_or_collective_spans():
    """The "request" sub-budget (util/events.py _CATEGORY_CAPS): a
    high-QPS span flood ages out against itself, never the task exec
    spans `ray-tpu timeline` is built on (the PR 5 budget pattern)."""
    _clean_events()
    tracing.record_exec("aa" * 8, "task", "keep_me", 1.0, 2.0)
    events.record("collective", "round", kind="allreduce", rank=0,
                  ts=1.0, dur=0.1)
    ctx = tracing.mint_context()
    cap = events._CATEGORY_CAPS["request"]
    for i in range(cap + 500):
        tracing.record_request_span("proxy", "handler", ctx,
                                    ctx.span_id, 1.0, 1.1)
    evs = events.dump()
    cats = [e.get("cat") for e in evs]
    assert cats.count("request") == cap          # aged against itself
    assert any(e.get("cat") == "trace" and e.get("target") == "keep_me"
               for e in evs)
    assert any(e.get("cat") == "collective" for e in evs)
    # the aggregation-point buffer applies the same sub-budget
    buf = events.CategoryBuffer(maxlen=events._DEFAULT_CAP)
    buf.extend(evs)
    agg = [e.get("cat") for e in buf.dump()]
    assert agg.count("request") == cap
    assert agg.count("trace") >= 1
    _clean_events()


# -- chrome rendering --------------------------------------------------------

def _req_ev(node, pid, comp, seg, trace, span, parent, ts, dur, **kw):
    return {"cat": "request", "name": "span", "node": node, "pid": pid,
            "component": comp, "seg": seg, "trace": trace, "span": span,
            "parent": parent, "ts": ts, "dur": dur, **kw}


def test_to_chrome_request_lanes_and_forward_flow_edges_under_skew():
    """Two processes on nodes whose clocks disagree by 80 ms: with the
    collected offsets applied, request lanes merge onto one corrected
    axis and every parent->child flow edge points forward in time."""
    t = 1000.0
    skew = 0.08
    ctx = tracing.mint_context()
    sid_root, sid_h, sid_r = (tracing.new_span_id() for _ in range(3))
    evs = [
        _req_ev("aaaa", 1, "proxy", "request", ctx.trace_id, sid_root,
                "", t, 0.2, root=True, status="ok", keep="sampled"),
        _req_ev("aaaa", 1, "handle", "submit", ctx.trace_id, sid_h,
                sid_root, t + 0.002, 0.004),
        # replica node's clock runs AHEAD by `skew`: raw child ts
        # precedes the parent's — only the offsets fix the ordering
        _req_ev("bbbb", 2, "replica", "handler", ctx.trace_id, sid_r,
                sid_h, t + 0.01 + skew, 0.15),
    ]
    recs = tracing.to_chrome(evs, clock_offsets={"aaaa": 0.0,
                                                 "bbbb": skew})
    lanes = {(r["pid"], r["tid"]) for r in recs if r["ph"] == "X"}
    assert ("node:aaaa", "req:proxy") in lanes
    assert ("node:aaaa", "req:handle") in lanes
    assert ("node:bbbb", "req:replica") in lanes
    starts = {r["id"]: r for r in recs if r["ph"] == "s"}
    finishes = {r["id"]: r for r in recs if r["ph"] == "f"}
    assert len(starts) == 2 and starts.keys() == finishes.keys()
    for fid, s in starts.items():
        assert finishes[fid]["ts"] >= s["ts"], (s, finishes[fid])


def test_to_chrome_trace_id_filter_reuses_the_renderer():
    ctx, other = tracing.mint_context(), tracing.mint_context()
    evs = [
        _req_ev("aaaa", 1, "proxy", "request", ctx.trace_id, "a" * 16,
                "", 1.0, 0.1, root=True),
        _req_ev("aaaa", 1, "proxy", "request", other.trace_id,
                "b" * 16, "", 1.0, 0.5, root=True),
        {"cat": "trace", "name": "exec", "task": "cc" * 8,
         "kind": "task", "target": "nested", "ts": 1.01, "dur": 0.02,
         "pid": 3, "trace": ctx.trace_id},
    ]
    recs = tracing.to_chrome(evs, trace_id=ctx.trace_id)
    spans = [r for r in recs if r["ph"] == "X"]
    assert len(spans) == 2
    assert {r["args"].get("trace") for r in spans} == {ctx.trace_id}
    assert any(r["name"] == "nested" for r in spans)


# -- exemplars ---------------------------------------------------------------

def test_histogram_exemplar_kept_per_bucket_and_rendered():
    from ray_tpu.util import metrics as m
    h = m.Histogram("zz_req_trace_test_s", "t", boundaries=(0.1, 1.0))
    tid = tracing.mint_context().trace_id
    h.observe(0.05, exemplar=tid)              # bucket 0 (le 0.1)
    h.observe(0.5)                             # bucket 1, no exemplar
    h.observe(5.0, exemplar="ee" * 16)         # +Inf bucket
    out = h.render()
    assert f'# {{trace_id="{tid}"}} 0.05' in out
    assert f'trace_id="{"ee" * 16}"' in out
    # the last exemplar per bucket wins
    tid2 = tracing.mint_context().trace_id
    h.observe(0.06, exemplar=tid2)
    out = h.render()
    assert tid2 in out and tid not in out
    # the push path (render_labeled) carries exemplars to the head:
    # they ride the sample line, not a stripped comment line
    labeled = m.render_labeled({"node": "n1"})
    assert tid2 in labeled
    # exemplar tails are OpenMetrics-only syntax: the classic text
    # format strips them (a stock Prometheus scrape would otherwise
    # reject every sample over the '#') while values/counts survive
    stripped = m.strip_exemplars(out)
    assert "trace_id=" not in stripped
    assert 'zz_req_trace_test_s_bucket{le="0.1"} 2' in stripped
    assert m.strip_exemplars(labeled).count("trace_id=") == 0
    with m._LOCK:
        m._REGISTRY.pop("zz_req_trace_test_s", None)


def test_metrics_endpoint_strips_exemplars_unless_opted_in():
    """The DEFAULT /metrics scrape must stay parseable by a stock
    Prometheus text-format parser (exemplar tails stripped — even for
    a scraper advertising OpenMetrics in Accept, which stock
    Prometheus does by default); ?exemplars=1 is the explicit opt-in
    that includes the tails."""
    import urllib.request

    from ray_tpu.util import metrics as m
    h = m.Histogram("zz_req_trace_srv_s", "t", boundaries=(1.0,))
    h.observe(0.5, exemplar="ab" * 16)

    async def go():
        srv = m.MetricsServer()
        host, port = await srv.start("127.0.0.1", 0)

        def fetch(path="/metrics", accept=None):
            req = urllib.request.Request(
                f"http://{host}:{port}{path}",
                headers={"Accept": accept} if accept else {})
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.headers.get("Content-Type"), r.read().decode()
        loop = asyncio.get_running_loop()
        classic = await loop.run_in_executor(None, fetch)
        negotiated = await loop.run_in_executor(
            None, lambda: fetch(
                accept="application/openmetrics-text;version=1.0.0"))
        opted = await loop.run_in_executor(
            None, lambda: fetch("/metrics?exemplars=1"))
        await srv.stop()
        return classic, negotiated, opted

    classic, negotiated, opted = asyncio.run(go())
    for ct, body in (classic, negotiated):
        assert ct.startswith("text/plain")
        assert "zz_req_trace_srv_s_bucket" in body
        assert "trace_id=" not in body
    ct, body = opted
    assert f'trace_id="{"ab" * 16}"' in body
    with m._LOCK:
        m._REGISTRY.pop("zz_req_trace_srv_s", None)


# -- engine spans + batch links ----------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    import jax

    from ray_tpu.models import llama
    cfg = llama.tiny(vocab_size=64, dim=32, n_layers=2, n_heads=2,
                     n_kv_heads=2, ffn_dim=64, dtype="float32",
                     logits_dtype="float32", attn_impl="reference")
    return cfg, llama.init_params(jax.random.PRNGKey(0), cfg)


def test_engine_records_queue_prefill_generate_and_linked_batch_spans(
        tiny_model):
    from ray_tpu.llm import LLMEngine
    cfg, params = tiny_model
    _clean_events()
    t1 = "aa" * 16
    t2 = "bb" * 16

    async def go():
        eng = LLMEngine(cfg, params, max_slots=2, max_len=64,
                        prefill_buckets=(8,), cache_dtype="float32",
                        steps_per_sync=4)

        async def one(tid):
            tok = tracing.set_request_context(
                tracing.TraceContext(tid, tracing.new_span_id()))
            try:
                return await eng.generate([3, 5, 7],
                                          max_new_tokens=12)
            finally:
                tracing.reset_request_context(tok)

        await asyncio.gather(one(t1), one(t2))
        await eng.stop()

    asyncio.run(go())
    evs = [e for e in events.dump() if e.get("cat") == "request"]
    for tid in (t1, t2):
        segs = {e["seg"] for e in evs if e.get("trace") == tid}
        assert {"queue", "prefill", "generate"} <= segs, (tid, segs)
        gen = [e for e in evs if e.get("trace") == tid
               and e["seg"] == "generate"]
        assert len(gen) == 1 and gen[0]["tokens"] == 12
    batches = [e for e in evs if e.get("name") == "batch"]
    assert batches, "no decode block spans"
    linked = set()
    for b in batches:
        assert b["seg"] == "decode" and b["links"]
        linked.update(b["links"])
    assert linked == {t1, t2}
    # the TTFT histogram carries a trace-id exemplar for its bucket
    from ray_tpu.util import metrics as m
    ttft = m._REGISTRY["llm_ttft_device_s"]
    assert any(x[0] in (t1, t2)
               for ex in ttft._exemplars.values() for x in ex.values())
    _clean_events()


def test_engine_failed_request_span_is_errored(tiny_model):
    from ray_tpu.llm import LLMEngine
    from ray_tpu.serve import fault
    cfg, params = tiny_model
    _clean_events()
    tid = "cd" * 16

    async def go():
        eng = LLMEngine(cfg, params, max_slots=1, max_len=64,
                        prefill_buckets=(8,), cache_dtype="float32")
        tok = tracing.set_request_context(
            tracing.TraceContext(tid, tracing.new_span_id()))
        try:
            with pytest.raises(fault.DeadlineExceeded):
                await eng.generate([1, 2], max_new_tokens=4,
                                   deadline_ts=time.time() + 0.05)
        finally:
            tracing.reset_request_context(tok)
        await eng.stop()

    asyncio.run(go())
    gen = [e for e in events.dump() if e.get("cat") == "request"
           and e.get("trace") == tid and e.get("seg") == "generate"]
    assert len(gen) == 1 and gen[0]["error"]
    _clean_events()


# -- knob lint ---------------------------------------------------------------

def test_trace_knobs_enumerated_and_exercised():
    """The folded knob lint (check_metrics_lint.lint_knob_tests) scans
    every registered family — chaos, tuner, AND the new trace knobs —
    with one shared helper; expected names are assembled at runtime so
    this file's own text can't satisfy the grep."""
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "scripts", "check_metrics_lint.py")
    spec = importlib.util.spec_from_file_location(
        "check_metrics_lint", os.path.abspath(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert set(mod.KNOB_FAMILIES) >= {"chaos", "tuner", "trace"}
    expect = {"_".join(["trace", "sample", "rate"]),
              "_".join(["trace", "slow", "threshold", "s"])}
    assert expect <= set(mod.trace_knobs()), mod.trace_knobs()
    assert mod.lint_knob_tests() == []
    assert mod.lint_knob_tests(families=["trace"]) == []
    bogus = "_".join(["trace", "no", "such", "knob"])
    errs = mod._lint_knob_tests("trace", [bogus])
    assert len(errs) == 1 and bogus in errs[0]


# -- live-cluster e2e --------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    # trace_sample_rate=0: ONLY error/deadline/slow traces surface in
    # the sampled list — the e2e asserts both sides of the tail
    # decision (healthy waterfalls still render; they just don't list)
    env = {"RAY_TPU_TRACE_SAMPLE_RATE": "0.0",
           "RAY_TPU_TRACE_SLOW_THRESHOLD_S": "30.0",
           "RAY_TPU_SERVE_DEFAULT_DEADLINE_S": "60"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    import ray_tpu
    ray_tpu.init(num_cpus=8)
    yield
    from ray_tpu import serve
    serve.shutdown()
    ray_tpu.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _post(addr, path, payload, deadline_s=None, traceparent=None):
    conn = http.client.HTTPConnection(addr["host"], addr["port"],
                                      timeout=60)
    headers = {"Content-Type": "application/json"}
    if deadline_s is not None:
        headers["X-Request-Deadline"] = str(deadline_s)
    if traceparent:
        headers["traceparent"] = traceparent
    conn.request("POST", path, body=json.dumps(payload),
                 headers=headers)
    r = conn.getresponse()
    out = {"status": r.status, "body": r.read(),
           "trace_id": r.getheader("X-Trace-Id")}
    conn.close()
    return out


def _collect_trace(tid, want, timeout_s=30.0):
    """Poll the cluster timeline until the trace's request spans cover
    ``want`` components (worker event buffers flush every ~1 s)."""
    import ray_tpu
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        evs = ray_tpu.timeline(all_nodes=True, trace_id=tid)
        comps = {e.get("component") for e in evs
                 if e.get("cat") == "request"}
        if want <= comps:
            return evs
        time.sleep(0.5)
    raise AssertionError(
        f"trace {tid}: components {comps} never covered {want}")


@pytest.mark.slow
def test_one_http_request_yields_a_cross_process_waterfall_e2e(
        cluster, tmp_path):
    """The acceptance drive: one HTTP request proxy -> handle ->
    replica -> engine on a live cluster yields ONE trace id whose
    waterfall has spans from >= 4 components across >= 2 processes
    with clock-corrected flow edges that never run backwards."""
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(max_ongoing_requests=4)
    class Gen:
        def __init__(self):
            import jax

            from ray_tpu.llm import LLMEngine
            from ray_tpu.models import llama
            cfg = llama.tiny(vocab_size=64, dim=32, n_layers=2,
                             n_heads=2, n_kv_heads=2, ffn_dim=64,
                             dtype="float32", logits_dtype="float32",
                             attn_impl="reference")
            params = llama.init_params(jax.random.PRNGKey(0), cfg)
            self.eng = LLMEngine(cfg, params, max_slots=2, max_len=64,
                                 prefill_buckets=(8,),
                                 cache_dtype="float32")

        async def __call__(self, v=None):
            out = await self.eng.generate((v or {}).get("tokens",
                                                        [3, 5, 7]),
                                          max_new_tokens=6)
            return {"n": len(out["tokens"])}

    serve.run(Gen.bind(), name="app_trace", route_prefix="/gen")
    addr = serve.proxy_address()
    r = _post(addr, "/gen", {"tokens": [3, 5, 7]}, deadline_s=30)
    assert r["status"] == 200, r
    tid = r["trace_id"]
    assert tid and len(tid) == 32
    evs = _collect_trace(
        tid, {"proxy", "handle", "replica", "engine"})
    req = [e for e in evs if e.get("cat") == "request"]
    comps = {e["component"] for e in req}
    assert {"proxy", "handle", "replica", "engine"} <= comps
    procs = {(str(e.get("node", ""))[:8], e.get("pid")) for e in req}
    assert len(procs) >= 2, procs
    # clock-corrected chrome waterfall: request lanes + forward flows
    out = str(tmp_path / "trace.json")
    recs = ray_tpu.timeline(all_nodes=True, chrome_path=out,
                            trace_id=tid)
    lanes = {x["tid"] for x in recs if x.get("ph") == "X"}
    assert {"req:proxy", "req:handle", "req:replica",
            "req:engine"} <= lanes, lanes
    starts = {x["id"]: x for x in recs if x.get("ph") == "s"}
    finishes = {x["id"]: x for x in recs if x.get("ph") == "f"}
    assert starts, "no flow edges"
    for fid, s in starts.items():
        assert finishes[fid]["ts"] >= s["ts"]
    with open(out) as f:
        assert json.load(f)["traceEvents"]
    # sampled OUT at rate 0: the healthy trace renders but isn't listed
    from ray_tpu.util.state import traces_from_events
    assert tid not in {t["trace_id"] for t in traces_from_events(
        ray_tpu.timeline(all_nodes=True))}
    # a client traceparent is JOINED, not replaced
    sent = tracing.mint_context()
    r2 = _post(addr, "/gen", {"tokens": [2, 4]}, deadline_s=30,
               traceparent=tracing.format_traceparent(sent))
    assert r2["status"] == 200 and r2["trace_id"] == sent.trace_id
    serve.delete("app_trace")


@pytest.mark.slow
def test_error_and_deadline_traces_survive_rate_zero_sampling_e2e(
        cluster):
    """An injected replica error and an expired deadline each produce
    a trace that survives tail sampling at trace_sample_rate=0."""
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(max_ongoing_requests=4)
    class Flaky:
        async def __call__(self, v=None):
            v = v or {}
            if v.get("boom"):
                raise ValueError("injected replica failure")
            await asyncio.sleep(float(v.get("sleep", 0)))
            return "ok"

    serve.run(Flaky.bind(), name="app_err", route_prefix="/err")
    addr = serve.proxy_address()
    r_err = _post(addr, "/err", {"boom": True}, deadline_s=30)
    assert r_err["status"] == 500 and r_err["trace_id"]
    r_dl = _post(addr, "/err", {"sleep": 10}, deadline_s=0.5)
    assert r_dl["status"] == 504 and r_dl["trace_id"]
    from ray_tpu.util.state import traces_from_events
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        rows = {t["trace_id"]: t for t in traces_from_events(
            ray_tpu.timeline(all_nodes=True))}
        if r_err["trace_id"] in rows and r_dl["trace_id"] in rows:
            break
        time.sleep(0.5)
    else:
        raise AssertionError(f"error/deadline traces never listed: "
                             f"{list(rows)[:5]}")
    assert rows[r_err["trace_id"]]["error"]
    assert rows[r_err["trace_id"]]["status"] == "error"
    assert rows[r_dl["trace_id"]]["status"] == "deadline"
    serve.delete("app_err")
