"""Elastic reshard planning units (train/reshard.py): pure math, no
cluster — N->N-1 and N->N+k plans, zero-size shards, contribution
embedding, coverage, and mirror-holder assignment. Late-alphabet module
name keeps the tier-1 870 s cutoff stable."""

import numpy as np
import pytest

from ray_tpu.train import reshard as rs


def _tiles(total, size):
    bounds = rs.all_bounds(total, size)
    assert bounds[0][0] == 0 and bounds[-1][1] == total
    for (alo, ahi), (blo, bhi) in zip(bounds, bounds[1:]):
        assert ahi == blo
    return bounds


def test_shard_bounds_tile_and_match_ring_formula():
    for total in (0, 1, 3, 7, 1000):
        for size in (1, 2, 3, 5, 8):
            _tiles(total, size)
            for r in range(size):
                lo, hi = rs.shard_bounds(total, size, r)
                assert (lo, hi) == (total * r // size,
                                    total * (r + 1) // size)
    with pytest.raises(ValueError):
        rs.shard_bounds(10, 4, 4)


def _check_plan(total, old_n, new_n, keep=None):
    moves = rs.plan_reshard(total, old_n, new_n, keep=keep)
    # every move is a genuine overlap of one old and one new segment
    for m in moves:
        olo, ohi = rs.shard_bounds(total, old_n, m.src)
        nlo, nhi = rs.shard_bounds(total, new_n, m.dst)
        assert olo <= m.lo < m.hi <= ohi
        assert nlo <= m.lo < m.hi <= nhi
    # the moves exactly tile the flat space (each coord moved once)
    covered = sorted((m.lo, m.hi) for m in moves)
    assert rs.coverage_gaps(total, covered) == []
    assert sum(hi - lo for lo, hi in covered) == total
    return moves


def test_plan_shrink_n_to_n_minus_1():
    moves = _check_plan(12, 4, 3)
    # rank 0's new segment [0,4) spans old rank 0's [0,3) fully plus
    # one element of old rank 1's — the minimal move set
    locals_ = [m for m in moves if m.local]
    wires = [m for m in moves if not m.local]
    assert locals_ and wires
    # identity keep: old rank r surviving as new rank r keeps its
    # overlap local
    for m in locals_:
        assert m.src == m.dst


def test_plan_grow_n_to_n_plus_k():
    moves = _check_plan(100, 3, 5)
    # growing strictly adds owners: every NEW rank receives something
    assert {m.dst for m in moves} == set(range(5))
    # moved (wire) bytes are only the non-local overlap
    wire = rs.moved_bytes(moves)
    assert 0 < wire < 4 * 100


def test_plan_zero_size_shards():
    # total < new size: some new shards are empty — no moves target them
    moves = _check_plan(3, 5, 2)
    moves2 = _check_plan(3, 2, 5)
    assert all(m.hi > m.lo for m in moves + moves2)
    # fully empty space: nothing to move anywhere
    assert rs.plan_reshard(0, 4, 3) == []


def test_plan_survivor_keep_map():
    # old rank 1 died; survivors 0,2 become new ranks 0,1
    keep = {0: 0, 2: 1}
    moves = rs.plan_reshard(9, 3, 2, keep=keep)
    for m in moves:
        assert m.local == (keep.get(m.src) == m.dst)
    # old rank 1's data is needed by SOME new rank but is never local
    assert any(m.src == 1 and not m.local for m in moves)


def test_contribution_embeds_disjoint_and_rejects_overlap():
    v = rs.contribution(10, [(0, 3, np.arange(3.)),
                             (7, 10, np.arange(3.))])
    assert v.tolist() == [0, 1, 2, 0, 0, 0, 0, 0, 1, 2]
    with pytest.raises(rs.ReshardError):
        rs.contribution(10, [(0, 5, np.zeros(5)), (4, 8, np.zeros(4))])
    with pytest.raises(rs.ReshardError):
        rs.contribution(10, [(0, 5, np.zeros(3))])   # length mismatch
    with pytest.raises(rs.ReshardError):
        rs.contribution(4, [(2, 6, np.zeros(4))])    # out of range


def test_coverage_gaps():
    assert rs.coverage_gaps(10, [(0, 10)]) == []
    assert rs.coverage_gaps(10, [(2, 4), (6, 8)]) == \
        [(0, 2), (4, 6), (8, 10)]
    assert rs.coverage_gaps(0, []) == []
    assert rs.coverage_gaps(5, []) == [(0, 5)]


def test_local_exchange_requires_full_coverage():
    out = rs.exchange(None, 6, [(0, 2, np.arange(2.)),
                                (2, 6, np.arange(4.))])
    assert out.tolist() == [0, 1, 0, 1, 2, 3]
    with pytest.raises(rs.ReshardError):
        rs.exchange(None, 6, [(0, 2, np.arange(2.))])


def test_assign_recovery_picks_freshest_mirror():
    inv = {0: {2: 5}, 1: {2: 9, 3: 1}, 3: {}}
    assert rs.assign_recovery([2], inv) == {2: 1}          # step 9 wins
    assert rs.assign_recovery([2, 3], inv) == {2: 1, 3: 1}
    assert rs.assign_recovery([4], inv) == {4: None}       # uncovered


class _FakeRing:
    """RingReducer-shaped double: 'reduce_scatter' sums the vectors
    every fake rank contributed and returns this rank's new slice —
    the exchange() contract without processes."""

    def __init__(self, rank, size, pool):
        self.rank, self.size, self.own = rank, size, rank
        self.pool = pool

    def seg_bounds(self, total, seg=None):
        s = self.rank if seg is None else seg
        return total * s // self.size, total * (s + 1) // self.size

    def reduce_scatter(self, value, op="sum"):
        assert op == "sum"
        self.pool.append(np.asarray(value, np.float64))
        full = np.sum(self.pool, axis=0)
        lo, hi = self.seg_bounds(full.size)
        return full[lo:hi]


def test_exchange_matches_plan_on_shrink():
    """Simulated 3->2 reshard: survivors (old ranks 0, 1) plus old
    rank 1 holding old rank 2's mirror reconstruct exactly the values
    the plan says each new rank owns."""
    total = 11
    state = np.arange(total, dtype=np.float64) * 1.5
    old = rs.all_bounds(total, 3)
    pieces = {
        0: [(old[0][0], old[0][1], state[old[0][0]:old[0][1]])],
        1: [(old[1][0], old[1][1], state[old[1][0]:old[1][1]]),
            # old rank 1 contributes the dead rank 2's mirror
            (old[2][0], old[2][1], state[old[2][0]:old[2][1]])],
    }
    outs = {}
    for new_rank in (0, 1):
        pool = []
        for contributor in (0, 1):
            ring = _FakeRing(new_rank, 2, pool)
            out = rs.exchange(ring, total, pieces[contributor])
        outs[new_rank] = out
    new = rs.all_bounds(total, 2)
    for r in (0, 1):
        lo, hi = new[r]
        np.testing.assert_allclose(outs[r], state[lo:hi])
