"""Serve-plane fault tolerance (serve/fault.py, serve/chaos.py,
Config.testing_serve_failure): admission control + shedding, deadline
propagation/cancellation (batch-slot reclaim), replica circuit
breakers, graceful draining, and the deterministic serve chaos plane —
the serving sibling of test_zz_channel_chaos.py. Late-alphabet module
name keeps the tier-1 870 s cutoff stable."""

import asyncio
import http.client
import json
import os
import threading
import time

import pytest

from ray_tpu.serve import fault
from ray_tpu.serve.chaos import ServeChaos, chaos_fire, reset_serve_chaos

pytestmark = pytest.mark.chaos


# -- chaos spec --------------------------------------------------------------

def test_serve_chaos_spec_parse_rejects_garbage():
    for bad in ("proxy", "proxy:error", "ingress:error:1",
                "proxy:explode:1", "proxy:error:0", "replica:drop:x",
                "proxy:drop:1"):       # drop is replica-site only
        with pytest.raises(ValueError):
            ServeChaos(bad)
    plan = ServeChaos("proxy:error:2,replica:delay:1:0.05,"
                      "replica:drop:3")
    assert len(plan.rules) == 3


def test_serve_chaos_counters_fire_on_exact_nth_request():
    plan = ServeChaos("proxy:error:3,replica:drop:1")
    assert plan.fire("proxy") is None
    assert plan.fire("replica") == ("drop", 0.1)   # replica op 1
    assert plan.fire("proxy") is None              # replicas don't count
    assert plan.fire("proxy") == ("error", 0.1)    # the 3rd proxy op
    assert plan.fire("proxy") is None              # one-shot
    assert plan.fire("replica") is None


def test_serve_chaos_config_knob_arms_and_disarms():
    """testing_serve_failure rides Config like the rpc/channel chaos
    knobs; reset_serve_chaos re-reads it (counters restart)."""
    from ray_tpu.config import Config, set_config
    try:
        set_config(Config.from_env(
            testing_serve_failure="proxy:delay:1:0.0"))
        reset_serve_chaos()
        assert chaos_fire("proxy") == ("delay", 0.0)
        assert chaos_fire("proxy") is None
    finally:
        set_config(Config.from_env(testing_serve_failure=""))
        reset_serve_chaos()
    assert chaos_fire("proxy") is None


def test_chaos_knob_lint_requires_a_test_per_knob():
    """check_metrics_lint also enforces that every testing_*_failure
    knob is exercised by some pytest (this module exercises
    testing_serve_failure)."""
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "scripts", "check_metrics_lint.py")
    spec = importlib.util.spec_from_file_location(
        "check_metrics_lint", os.path.abspath(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    knobs = mod.chaos_knobs()
    assert "testing_serve_failure" in knobs
    assert "testing_channel_failure" in knobs
    assert mod.lint_chaos_knob_tests() == []
    # a knob no test mentions is flagged (name assembled so THIS file
    # doesn't satisfy the grep)
    fake = "_".join(["testing", "bogus", "failure"])
    errs = mod.lint_chaos_knob_tests(knobs=[fake])
    assert len(errs) == 1 and fake in errs[0]


def test_fault_metrics_registered():
    m = fault.fault_metrics()
    names = {x.name for x in m.values()}
    assert names == {"serve_shed_total", "serve_retries_total",
                     "serve_deadline_exceeded_total",
                     "serve_replica_ejected", "serve_drain_wait_s"}


# -- deadlines + budgeted retries --------------------------------------------

def test_deadline_context_and_remaining():
    assert fault.current_deadline_ts() is None
    assert fault.remaining_s(None) is None
    tok = fault.set_request_deadline(time.time() + 5.0)
    try:
        assert 4.0 < fault.remaining_s(fault.current_deadline_ts()) <= 5.0
    finally:
        fault.reset_request_deadline(tok)
    assert fault.current_deadline_ts() is None


def test_retry_policy_is_deadline_capped():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("x")

    # spent budget: exactly ONE attempt, no sleeping
    p = fault.RetryPolicy(max_attempts=5, base_backoff_s=0.01)
    with pytest.raises(ValueError):
        p.run(boom, deadline_ts=time.time() - 1.0)
    assert len(calls) == 1
    # generous budget: attempt-capped with jittered backoff
    calls.clear()
    with pytest.raises(ValueError):
        p.run(boom, deadline_ts=time.time() + 30.0)
    assert len(calls) == 5
    # jitter bounds: uniform in (0, base * 2^attempt]
    for attempt in range(4):
        for _ in range(16):
            b = p.backoff_s(attempt)
            assert 0.0 <= b <= 0.01 * (2 ** attempt) + 1e-9
    # non-retryable errors surface immediately
    calls.clear()
    with pytest.raises(ValueError):
        p.run(boom, retryable=lambda e: False)
    assert len(calls) == 1


def test_classify_error_buckets():
    from ray_tpu.runtime.core import (ActorDiedError, GetTimeoutError,
                                      TaskError)
    assert fault.classify_error(fault.DeadlineExceeded("x")) == "deadline"
    assert fault.classify_error(
        TaskError("tb", cause=fault.DeadlineExceeded("x"))) == "deadline"
    assert fault.classify_error(
        TaskError("tb", cause=fault.ReplicaDraining("x"))) == "draining"
    assert fault.classify_error(GetTimeoutError("t")) == "timeout"
    assert fault.classify_error(ActorDiedError("d")) == "infra"
    assert fault.classify_error(TaskError("user code raised")) == "user"
    assert fault.classify_error(ValueError("v")) == "user"


# -- circuit breaker ---------------------------------------------------------

def test_circuit_breaker_eject_half_open_cycle():
    clock = [0.0]
    b = fault.CircuitBreaker(failure_threshold=3, cooldown_s=2.0,
                             clock=lambda: clock[0])
    assert b.state == fault.CLOSED and b.allow()
    b.record_failure()
    b.record_failure()
    assert b.state == fault.CLOSED      # not yet consecutive enough
    b.record_success()
    b.record_failure()
    b.record_failure()
    b.record_failure()                  # 3 consecutive: eject
    assert b.state == fault.OPEN and not b.allow()
    clock[0] = 1.9
    assert not b.allow()                # still cooling down
    clock[0] = 2.1
    assert b.allow()                    # half-open: one trial
    assert b.state == fault.HALF_OPEN
    assert not b.allow()                # second concurrent trial denied
    b.record_failure()                  # trial failed: re-open
    assert b.state == fault.OPEN and not b.allow()
    clock[0] = 4.5
    assert b.allow()
    b.record_success()                  # trial succeeded: closed
    assert b.state == fault.CLOSED and b.allow()


def test_circuit_breaker_probe_shortcuts_and_extends():
    clock = [0.0]
    b = fault.CircuitBreaker(failure_threshold=1, cooldown_s=10.0,
                             clock=lambda: clock[0])
    b.record_failure()
    assert b.state == fault.OPEN
    b.force_half_open()                 # ping probe succeeded
    assert b.state == fault.HALF_OPEN and b.allow()
    b.record_failure()
    assert b.state == fault.OPEN
    clock[0] = 9.0
    b.extend_open()                     # probe failed: restart cooldown
    clock[0] = 11.0                     # would have half-opened at 10+9?
    assert not b.allow()                # no: cooldown restarted at t=9
    clock[0] = 19.5
    assert b.allow()


def test_circuit_breaker_latency_ejection():
    clock = [0.0]
    b = fault.CircuitBreaker(failure_threshold=3, cooldown_s=1.0,
                             latency_threshold_s=0.5, latency_count=2,
                             clock=lambda: clock[0])
    b.record_success(0.6)
    b.record_success(0.1)               # streak broken
    b.record_success(0.6)
    assert b.state == fault.CLOSED
    b.record_success(0.7)               # 2 consecutive slow: eject
    assert b.state == fault.OPEN


def test_router_pick_gives_half_open_trial_priority():
    """A recovering replica must get its ONE trial request even while
    healthy replicas exist — without priority, the closed majority
    starves the trial and the replica stays ejected forever."""
    from ray_tpu.serve.handle import _Router
    r = _Router("d")
    a, b = b"a" * 8, b"b" * 8
    r.replicas = [a, b]
    clock = [0.0]
    br = fault.CircuitBreaker(failure_threshold=1, cooldown_s=1.0,
                              clock=lambda: clock[0])
    r.breakers[a] = br
    br.record_failure()                   # a ejected
    assert r.pick() == b                  # cooling down: skip a
    clock[0] = 1.5
    assert r.pick() == a                  # cooldown elapsed: the trial
    assert br.state == fault.HALF_OPEN
    assert r.pick() == b                  # trial in flight: healthy only
    br.record_success()
    assert br.state == fault.CLOSED       # decided: a rejoins the pool
    assert set(r.pick() for _ in range(20)) == {a, b}


# -- proxy admission control -------------------------------------------------

def _admission(capacity, queue_limit, ewma=0.01):
    from ray_tpu.config import Config, set_config
    from ray_tpu.serve.proxy import _Admission
    set_config(Config.from_env(serve_queue_limit=queue_limit))
    adm = _Admission("dep")
    adm._capacity = lambda: capacity
    adm.ewma_s = ewma
    return adm


def test_admission_sheds_at_queue_limit():
    from ray_tpu.serve.proxy import _Shed

    async def go():
        adm = _admission(capacity=1, queue_limit=2)
        dl = time.time() + 30.0
        assert await adm.acquire(dl) == 0.0      # within capacity
        w1 = asyncio.ensure_future(adm.acquire(dl))
        w2 = asyncio.ensure_future(adm.acquire(dl))
        await asyncio.sleep(0.05)                # both queued
        with pytest.raises(_Shed) as ei:
            await adm.acquire(dl)                # queue full: shed
        assert ei.value.retry_after_s >= 1.0
        adm.release()                            # slot -> oldest waiter
        assert (await w1) > 0.0
        adm.release()
        await w2
        adm.release()
        adm.release()
        assert adm.inflight == 0 and not adm.waiters

    asyncio.run(go())


def test_admission_sheds_when_predicted_wait_exceeds_budget():
    from ray_tpu.serve.proxy import _Shed

    async def go():
        # EWMA service time 10s, capacity 1: any queued request with a
        # 1s budget is predicted to miss — shed instantly, no parking
        adm = _admission(capacity=1, queue_limit=64, ewma=10.0)
        await adm.acquire(time.time() + 30.0)
        t0 = time.monotonic()
        with pytest.raises(_Shed):
            await adm.acquire(time.time() + 1.0)
        assert time.monotonic() - t0 < 0.2       # fast 503, no wait
        adm.release()

    asyncio.run(go())


def test_admission_sheds_queued_request_at_deadline():
    from ray_tpu.serve.proxy import _Shed

    async def go():
        adm = _admission(capacity=1, queue_limit=8)
        await adm.acquire(time.time() + 30.0)
        t0 = time.monotonic()
        with pytest.raises(_Shed):
            await adm.acquire(time.time() + 0.3)  # queued, then budget
        waited = time.monotonic() - t0            # runs out
        assert 0.2 < waited < 2.0
        adm.release()
        assert adm.inflight == 0

    asyncio.run(go())


# -- batching: cancelled waiters reclaim their slots -------------------------

def test_batch_queue_drops_cancelled_waiters():
    from ray_tpu.serve.batching import _BatchQueue

    async def go():
        seen = []

        async def fn(items):
            seen.append(list(items))
            return [i * 2 for i in items]

        q = _BatchQueue(fn, max_batch_size=8, batch_wait_timeout_s=0.1)
        t1 = asyncio.ensure_future(q.submit(1))
        t2 = asyncio.ensure_future(q.submit(2))
        await asyncio.sleep(0.01)
        t1.cancel()                      # deadline'd caller walks away
        with pytest.raises(asyncio.CancelledError):
            await t1
        assert await t2 == 4
        # the flushed batch never contained the cancelled item
        assert seen == [[2]]

    asyncio.run(go())


# -- engine: deadline cancellation reclaims batch slots ----------------------

@pytest.fixture(scope="module")
def tiny_model():
    import jax

    from ray_tpu.models import llama
    cfg = llama.tiny(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, ffn_dim=128, dtype="float32",
                     logits_dtype="float32", attn_impl="reference")
    return cfg, llama.init_params(jax.random.PRNGKey(0), cfg)


def test_engine_deadline_cancel_reclaims_batch_slot(tiny_model):
    """A ONE-slot engine: a long request whose budget expires mid-
    generation is cancelled (typed DeadlineExceeded), its slot is
    reclaimed, and a queued request then runs to completion — plus a
    queued request whose budget dies while WAITING fails fast without
    ever being admitted."""
    from ray_tpu.llm import LLMEngine
    cfg, params = tiny_model

    async def go():
        eng = LLMEngine(cfg, params, max_slots=1, max_len=4096,
                        prefill_buckets=(8,), cache_dtype="float32",
                        steps_per_sync=4)
        long_req = asyncio.ensure_future(eng.generate(
            [3, 7, 11], max_new_tokens=3000,
            deadline_ts=time.time() + 0.4))
        await asyncio.sleep(0.05)
        # queued behind the long request with a budget that dies first
        doomed = asyncio.ensure_future(eng.generate(
            [5, 9], max_new_tokens=4,
            deadline_ts=time.time() + 0.05))
        # queued with no deadline: must run once the slot frees
        follow = asyncio.ensure_future(eng.generate(
            [2, 4, 6], max_new_tokens=4))
        with pytest.raises(fault.DeadlineExceeded):
            await doomed
        with pytest.raises(fault.DeadlineExceeded):
            await long_req
        out = await follow
        assert len(out["tokens"]) == 4
        assert eng._slots == [None]       # every slot reclaimed
        await eng.stop()

    asyncio.run(go())


def test_engine_rejects_expired_submission(tiny_model):
    from ray_tpu.llm import LLMEngine
    cfg, params = tiny_model

    async def go():
        eng = LLMEngine(cfg, params, max_slots=1, max_len=64,
                        prefill_buckets=(8,), cache_dtype="float32")
        with pytest.raises(fault.DeadlineExceeded):
            await eng.generate([1, 2], max_new_tokens=4,
                               deadline_ts=time.time() - 1.0)
        await eng.stop()

    asyncio.run(go())


def test_engine_stream_deadline_cuts_mid_generation(tiny_model):
    from ray_tpu.llm import LLMEngine
    cfg, params = tiny_model

    async def go():
        eng = LLMEngine(cfg, params, max_slots=1, max_len=4096,
                        prefill_buckets=(8,), cache_dtype="float32",
                        steps_per_sync=4)
        got = []
        with pytest.raises(RuntimeError) as ei:
            async for tok in eng.generate_stream(
                    [3, 5], max_new_tokens=3000,
                    deadline_ts=time.time() + 0.4):
                got.append(tok)
        assert isinstance(ei.value, fault.DeadlineExceeded)
        assert 0 < len(got) < 3000        # produced some, then was cut
        assert eng._slots == [None]
        await eng.stop()

    asyncio.run(go())


# -- cluster e2e -------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    env = {"RAY_TPU_SERVE_QUEUE_LIMIT": "2",
           "RAY_TPU_SERVE_DEFAULT_DEADLINE_S": "60",
           "RAY_TPU_SERVE_DRAIN_TIMEOUT_S": "20"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    import ray_tpu
    ray_tpu.init(num_cpus=8)
    yield
    from ray_tpu import serve
    serve.shutdown()
    ray_tpu.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _post(addr, path, payload, deadline_s=None, accept=None):
    conn = http.client.HTTPConnection(addr["host"], addr["port"],
                                      timeout=60)
    headers = {"Content-Type": "application/json"}
    if deadline_s is not None:
        headers["X-Request-Deadline"] = str(deadline_s)
    if accept:
        headers["Accept"] = accept
    t0 = time.monotonic()
    conn.request("POST", path, body=json.dumps(payload), headers=headers)
    r = conn.getresponse()
    body = r.read()
    out = {"status": r.status, "body": body,
           "retry_after": r.getheader("Retry-After"),
           "elapsed_s": time.monotonic() - t0}
    conn.close()
    return out


def test_proxy_deadline_budget_returns_fast_504_e2e(cluster):
    """A slow replica + a small X-Request-Deadline: the client gets a
    fast 504, never the old fixed 120 s get_async ride."""
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(max_ongoing_requests=4)
    class Sleepy:
        async def __call__(self, v=None):
            await asyncio.sleep(10.0)
            return "done"

    h = serve.run(Sleepy.bind(), name="app_dl", route_prefix="/dl")
    addr = serve.proxy_address()
    r = _post(addr, "/dl", "x", deadline_s=0.6)
    assert r["status"] == 504, r
    assert r["elapsed_s"] < 5.0, r
    serve.delete("app_dl")


def test_proxy_sheds_overload_with_fast_503_e2e(cluster):
    """Offered load past capacity + a full bounded queue: fast 503s
    with Retry-After while admitted requests complete."""
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(max_ongoing_requests=1, num_replicas=1)
    class Slow:
        async def __call__(self, v=None):
            await asyncio.sleep(0.8)
            return "ok"

    serve.run(Slow.bind(), name="app_shed", route_prefix="/shed")
    addr = serve.proxy_address()
    # warmup fetches the routing table into the proxy's router so
    # admission sees real capacity (1 replica x 1 ongoing)
    assert _post(addr, "/shed", "w", deadline_s=10)["status"] == 200
    results = [None] * 6
    def one(i):
        results[i] = _post(addr, "/shed", i, deadline_s=6)
    threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    codes = [r["status"] for r in results]
    shed = [r for r in results if r["status"] == 503]
    assert codes.count(200) >= 1, codes
    assert len(shed) >= 1, codes
    for s in shed:
        assert s["retry_after"] is not None
        assert s["elapsed_s"] < 2.0, s     # fast rejection, no parking
    serve.delete("app_shed")


@pytest.mark.slow
def test_draining_replica_completes_streaming_e2e(cluster):
    """Redeploy marks the serving replica DRAINING: the in-flight
    STREAM runs to completion on the old replica (zero lost items)
    while new requests land on the replacement."""
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(max_ongoing_requests=4, num_replicas=1)
    class Streamer:
        def __init__(self, tag="v1"):
            self.tag = tag

        def __call__(self, v=None):
            return self.tag

        async def generate_stream(self, tokens, **kw):
            for i in range(int(tokens)):
                await asyncio.sleep(0.1)
                yield i

    h = serve.run(Streamer.bind("v1"), name="app_drain",
                  route_prefix=None)
    assert ray_tpu.get(h.remote(), timeout=30) == "v1"

    got = []
    err = []

    def consume():
        try:
            from ray_tpu.serve.llm import stream_generate
            for item in stream_generate(h, 30):
                got.append(item)
        except BaseException as e:  # noqa: BLE001
            err.append(e)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.5)              # stream is mid-flight on the old replica
    serve.run(Streamer.bind("v2"), name="app_drain", route_prefix=None)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            if ray_tpu.get(h.remote(), timeout=10) == "v2":
                break
        except Exception:
            pass
        time.sleep(0.2)
    else:
        pytest.fail("upgrade never took effect")
    t.join(timeout=30)
    assert not t.is_alive(), "stream never finished"
    assert not err, f"stream died during drain: {err}"
    assert got == list(range(30)), f"lost items: {len(got)}/30"
    serve.delete("app_drain")


@pytest.mark.slow
def test_replica_chaos_error_trips_breaker_and_recovers_e2e(cluster):
    """testing_serve_failure at the proxy boundary: consecutive
    injected submission failures are retried under the budgeted policy
    and the deployment keeps answering."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.config import Config, set_config

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, v=None):
            return f"e:{v}"

    h = serve.run(Echo.bind(), name="app_cb", route_prefix=None)
    assert ray_tpu.get(h.remote(0), timeout=30) == "e:0"
    try:
        set_config(Config.from_env(
            testing_serve_failure="proxy:error:2,proxy:error:3"))
        reset_serve_chaos()
        # request 2 fails its first two routing attempts (injected),
        # succeeds on the budgeted third
        out = ray_tpu.get(
            [h.options(deadline_s=20).remote(i) for i in range(1, 5)],
            timeout=60)
        assert out == [f"e:{i}" for i in range(1, 5)]
    finally:
        set_config(Config.from_env(testing_serve_failure=""))
        reset_serve_chaos()
    serve.delete("app_cb")
