"""Speculative decoding (llm/spec.py + the engine verify path):
prompt-lookup drafter behavior + accept-rate backoff, greedy and
rejection-sampling acceptance, the shared sampler filter transform
(lm.filter_logits) host/device parity, kvcache.truncate_seq rollback
properties, verify-width compile discipline, and engine-level
exact-match parity of speculative greedy decode against vanilla.

(Late-alphabet name keeps the tier-1 870 s cutoff stable.)
"""

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.config import get_config
from ray_tpu.llm import kvcache as kc
from ray_tpu.llm import model as lm
from ray_tpu.llm import spec
from ray_tpu.llm.engine import LLMEngine
from ray_tpu.models import llama


@pytest.fixture(scope="module")
def tiny_model():
    cfg = llama.tiny(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, ffn_dim=128, dtype="float32",
                     logits_dtype="float32", attn_impl="reference")
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _prompt(seed, n):
    return [int(x) for x in
            np.random.default_rng(seed).integers(1, 127, n)]


def _periodic_prompt(seed, n=64, period=16):
    pat = _prompt(seed, period)
    return (pat * (n // period + 1))[:n]


def _metric_sum(name) -> float:
    from ray_tpu.util import metrics as m
    mm = m._REGISTRY.get(name)
    return sum(mm._values.values()) if mm is not None else 0.0


# --- width buckets ----------------------------------------------------


def test_width_buckets():
    assert spec.width_buckets(1) == (2,)
    assert spec.width_buckets(2) == (2, 3)
    assert spec.width_buckets(4) == (2, 3, 5)
    assert spec.width_buckets(8) == (2, 3, 5, 9)
    # non-power-of-two k caps the top bucket at k+1
    assert spec.width_buckets(6) == (2, 3, 5, 7)
    with pytest.raises(ValueError):
        spec.width_buckets(0)


def test_bucket_width_rounds_up():
    b = spec.width_buckets(4)
    assert [spec.bucket_width(b, w) for w in (1, 2, 3, 4, 5)] \
        == [2, 2, 3, 5, 5]


# --- prompt-lookup drafter --------------------------------------------


def test_drafter_matches_periodic_history():
    d = spec.PromptLookupDrafter(k=4, ngram_max=3)
    hist = [1, 2, 3, 4] * 5
    # suffix [2,3,4] recurs; the 4 tokens after a match are 1,2,3,4
    assert d.propose(hist) == [1, 2, 3, 4]
    # max_k clamps the draft below k
    assert d.propose(hist, 2) == [1, 2]
    assert d.propose(hist, 0) == []


def test_drafter_no_match_on_unique_history():
    d = spec.PromptLookupDrafter(k=4, ngram_max=3)
    assert d.propose(list(range(40))) == []


def test_drafter_prefers_full_continuation():
    # constant stream: the NEAREST suffix match sits flush against the
    # end of history and has almost no continuation; the drafter must
    # take an earlier match with k tokens after it
    d = spec.PromptLookupDrafter(k=5, ngram_max=3)
    assert d.propose([7] * 20) == [7] * 5


def test_drafter_backoff_and_reprobe():
    d = spec.PromptLookupDrafter(k=4, ngram_max=2, window=8)
    hist = [7] * 30
    # 8 drafted tokens, 0 accepted -> window trips, cooldown = 4
    d.record(4, 0)
    d.record(4, 0)
    for _ in range(4):
        assert d.propose(hist) == []    # cooling off
    assert d.propose(hist) == [7] * 4   # probe round
    # healthy acceptance resets the backoff escalation
    d.record(4, 4)
    d.record(4, 4)
    assert d._backoff == 4
    assert d.accept_rate == pytest.approx(8 / 16)


def test_drafter_backoff_escalates():
    d = spec.PromptLookupDrafter(k=4, ngram_max=2, window=4)
    d.record(4, 0)
    assert d._cooldown == 4 and d._backoff == 8
    for _ in range(4):
        d.propose([7] * 10)
    d.record(4, 0)      # probe failed too
    assert d._cooldown == 8 and d._backoff == 16


# --- acceptance -------------------------------------------------------


def _rows(*argmaxes, v=16):
    """(len(argmaxes), v) logits with the requested per-row argmax."""
    out = np.random.default_rng(0).normal(size=(len(argmaxes), v))
    out = out.astype(np.float32)
    for j, t in enumerate(argmaxes):
        out[j, t] = out[j].max() + 2.0
    return out


def test_accept_greedy_prefix_and_bonus():
    logits = _rows(3, 5, 7, 9)
    rng = np.random.default_rng(0)
    # full agreement: k drafts + bonus from the last row
    emitted, n = spec.accept_tokens(
        logits, [3, 5, 7], temperature=0.0, top_k=0, top_p=1.0, rng=rng)
    assert (emitted, n) == ([3, 5, 7, 9], 3)
    # first disagreement stops acceptance; its row's argmax is emitted
    emitted, n = spec.accept_tokens(
        logits, [3, 6, 7], temperature=0.0, top_k=0, top_p=1.0, rng=rng)
    assert (emitted, n) == ([3, 5], 1)
    # empty draft degenerates to one greedy token
    emitted, n = spec.accept_tokens(
        logits[:1], [], temperature=0.0, top_k=0, top_p=1.0, rng=rng)
    assert (emitted, n) == ([3], 0)


def test_accept_rejection_sampling_preserves_distribution():
    """The spec-sampling guarantee: whatever the (deterministic) draft
    token is, the FIRST emitted token of a round is an exact sample
    from the model's filtered distribution p — accept-with-prob-p(d)
    plus zeroed-renormalized resampling must compose back to p."""
    v = 4
    logits = np.log(np.array([.45, .3, .2, .05], np.float64))
    logits = logits.astype(np.float32)[None]
    p_ref = spec.host_probs(logits[0], 1.0, 0, 1.0)
    rng = np.random.default_rng(7)
    n = 4000
    for d in (0, 3):    # a likely draft and an unlikely one
        counts = np.zeros(v)
        for _ in range(n):
            emitted, _na = spec.accept_tokens(
                np.concatenate([logits, logits]), [d],
                temperature=1.0, top_k=0, top_p=1.0, rng=rng)
            counts[emitted[0]] += 1
        emp = counts / n
        assert np.abs(emp - p_ref).max() < 0.04, (d, emp, p_ref)


# --- shared sampler filter (satellite: one transform, no drift) -------


def test_filter_logits_host_device_parity():
    rng = np.random.default_rng(2)
    logits = rng.normal(size=(4, 64)).astype(np.float32) * 3
    temps = np.array([1.0, 0.7, 1.3, 0.9], np.float32)
    top_ks = np.array([0, 5, 1, 64], np.int32)
    top_ps = np.array([1.0, 0.7, 0.3, 1.0], np.float32)
    scaled = logits / np.maximum(temps, 1e-6)[:, None]
    host = lm.filter_logits(scaled, top_ks, top_ps)
    dev = np.asarray(lm.filter_logits(
        jnp.asarray(scaled), jnp.asarray(top_ks), jnp.asarray(top_ps)))
    # identical mask pattern, near-identical surviving logits
    assert (np.isneginf(host) == np.isneginf(dev)).all()
    hf, df = host[np.isfinite(host)], dev[np.isfinite(dev)]
    np.testing.assert_allclose(hf, df, rtol=1e-5, atol=1e-6)
    # the masks actually did something in this fixture
    assert np.isneginf(host).any()
    # top_k=1 row keeps exactly one candidate
    assert np.isfinite(host[2]).sum() == 1


def test_device_sample_uses_shared_filter_greedy_unchanged():
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(size=(3, 32)).astype(np.float32))
    temps = jnp.zeros((3,), jnp.float32)
    out = lm.sample(logits, temps, jax.random.PRNGKey(0),
                    jnp.ones((3,), jnp.float32),
                    jnp.zeros((3,), jnp.int32))
    assert (np.asarray(out) == np.argmax(np.asarray(logits), -1)).all()


def test_host_probs_matches_device_softmax():
    rng = np.random.default_rng(4)
    logits = rng.normal(size=(48,)).astype(np.float32) * 2
    p_host = spec.host_probs(logits, 0.8, 6, 0.9)
    scaled = jnp.asarray(logits[None]) / 0.8
    masked = lm.filter_logits(scaled, jnp.asarray([6], jnp.int32),
                              jnp.asarray([0.9], jnp.float32))
    p_dev = np.asarray(jax.nn.softmax(masked, axis=-1))[0]
    np.testing.assert_allclose(p_host, p_dev, rtol=1e-4, atol=1e-6)
    assert p_host.sum() == pytest.approx(1.0)


# --- kvcache.truncate_seq (satellite: rollback correctness) -----------


def _pool_state(m):
    return (m.used_blocks(), m.cached_blocks(), m.free_blocks(),
            sorted(m.entries.keys()), dict(m.ref))


def test_truncate_noop_under_full_horizon_reservation():
    """The engine path: min_blocks pins the admission reservation, so
    a rejected-draft rollback changes NO pool state (the rollback is
    hash-chain/bookkeeping honesty, not block churn)."""
    m = kc.KVBlockManager(32, 8, table_width=8)
    toks = _prompt(3, 16)
    m.alloc_seq("a", toks, 16)          # 4 blocks reserved
    st0 = _pool_state(m)
    freed = m.truncate_seq("a", 17, min_blocks=m.blocks_needed(16, 16))
    assert freed == []
    assert _pool_state(m) == st0


def test_truncate_fork_draft_rollback_restores_pool_state():
    """fork -> COW-write draft blocks -> truncate -> free yields pool
    state identical to never having drafted."""
    def run(draft):
        m = kc.KVBlockManager(32, 8, table_width=8)
        toks = _prompt(4, 16)
        m.alloc_seq("a", toks, 16)
        m.fork_seq("a", "b")
        if draft:
            # draft tokens land in logical block 2: shared -> COW copy
            cw = m.ensure_writable("b", 2)
            assert cw is not None
            # rollback the branch to the shared 16 tokens: the private
            # copy frees, the shared blocks drop one reference
            freed = m.truncate_seq("b", 16)
            assert cw[1] in freed
        m.free_seq("b", cache=False)
        m.free_seq("a", toks)
        return _pool_state(m)

    assert run(draft=True) == run(draft=False)


def test_truncated_tail_never_satisfies_prefix_hit():
    m = kc.KVBlockManager(32, 8, table_width=8)
    stream = _prompt(5, 32)             # 4 full blocks, all hashed
    m.alloc_seq("a", stream, 8)
    assert len(m.seqs["a"].hashes) == 4
    # roll back to 16 tokens: the tail's hash-chain entries die with it
    m.truncate_seq("a", 16)
    assert len(m.seqs["a"].hashes) == 2
    m.free_seq("a")
    assert m.cached_blocks() == 2
    hit, _phys = m.lookup(stream)
    assert hit == 16                    # never the truncated 4 blocks


def test_truncate_then_free_with_stream_stops_at_trash():
    """free_seq re-extends the (cut) hash chain over the full stream,
    but the truncated table rows are trash — the insert walk must stop
    there instead of indexing freed blocks."""
    m = kc.KVBlockManager(32, 8, table_width=8)
    stream = _prompt(6, 32)
    m.alloc_seq("a", stream, 8)
    m.truncate_seq("a", 16)
    m.free_seq("a", stream)
    assert m.cached_blocks() == 2
    hit, _ = m.lookup(stream)
    assert hit == 16


def test_truncate_preserves_shared_prefix_refcounts():
    """Truncating one holder of a cached/shared prefix must not free
    or un-index blocks other holders (or the prefix index) own."""
    m = kc.KVBlockManager(32, 8, table_width=8)
    toks = _prompt(7, 24)
    m.alloc_seq("a", toks, 8)
    m.free_seq("a", toks)               # 3 full blocks cached
    b = m.alloc_seq("b", toks, 8)
    assert b["hit_tokens"] == 16        # capped one short of prompt
    cached_before = m.cached_blocks()
    free_before = m.free_blocks()
    freed = m.truncate_seq("b", 8)      # cut INTO the shared prefix
    # 3 blocks RELEASED: b's two fresh horizon blocks return to the
    # free list, but the shared hit block merely drops b's reference —
    # it stays in the prefix index (refcount 0 = cached/evictable)
    assert len(freed) == 3
    assert m.free_blocks() == free_before + 2
    assert m.cached_blocks() == cached_before + 1
    hit, _ = m.lookup(toks)
    assert hit == 16                    # index fully intact
    m.free_seq("b", cache=False)
    assert m.used_blocks() == 0


def test_truncate_unknown_seq_raises():
    m = kc.KVBlockManager(8, 8, table_width=4)
    with pytest.raises(KeyError):
        m.truncate_seq("nope", 8)


# --- engine: speculative greedy == vanilla greedy ---------------------


def _run_engine(cfg, params, prompts, *, spec_on, max_new=48,
                temperature=0.0, top_k=0, top_p=1.0, eos_id=None,
                **engine_kw):
    async def go():
        eng = LLMEngine(cfg, params, max_slots=4, max_len=256,
                        prefill_buckets=(64, 128),
                        cache_dtype="float32", kv_block_size=16,
                        spec=spec_on, **engine_kw)
        outs = await asyncio.gather(*[
            eng.generate(p, max_new_tokens=max_new,
                         temperature=temperature, top_k=top_k,
                         top_p=top_p, eos_id=eos_id)
            for p in prompts])
        st = eng.stats
        await eng.stop()
        return [o["tokens"] for o in outs], st
    return asyncio.run(go())


def test_spec_greedy_exact_match_parity(tiny_model):
    """The tentpole contract: speculative greedy output is token-for-
    token identical to vanilla greedy decode — across a high-accept
    periodic prompt, a low-accept one, and everything between."""
    cfg, params = tiny_model
    for seed in (9, 4, 0, 5):
        prompt = _periodic_prompt(seed)
        van, _ = _run_engine(cfg, params, [prompt], spec_on=False)
        spc, st = _run_engine(cfg, params, [prompt], spec_on=True)
        assert spc == van, f"seed {seed} diverged"
        assert st["spec"] is True


def test_spec_accept_rate_telemetry_populated(tiny_model):
    cfg, params = tiny_model
    drafted0 = _metric_sum("llm_spec_tokens_total")
    _, _st = _run_engine(cfg, params, [_periodic_prompt(9)],
                         spec_on=True)
    from ray_tpu.util import metrics as m
    tok = m._REGISTRY["llm_spec_tokens_total"]
    by_kind = {dict(k).get("kind"): v for k, v in tok._values.items()}
    assert by_kind.get("drafted", 0) > 0
    assert by_kind.get("accepted", 0) > 0
    assert _metric_sum("llm_spec_tokens_total") > drafted0
    rate = m._REGISTRY["llm_spec_accept_rate"]
    assert 0.0 < sum(rate._values.values()) <= 1.0


def test_spec_mixed_cobatch_keeps_greedy_parity(tiny_model):
    """A greedy request co-batched with a sampling request (mixed
    accepted lengths per round) still exact-matches its solo vanilla
    stream."""
    cfg, params = tiny_model
    greedy_prompt = _periodic_prompt(9)
    van, _ = _run_engine(cfg, params, [greedy_prompt], spec_on=False)

    async def go():
        eng = LLMEngine(cfg, params, max_slots=4, max_len=256,
                        prefill_buckets=(64, 128),
                        cache_dtype="float32", kv_block_size=16,
                        spec=True)
        a, b = await asyncio.gather(
            eng.generate(greedy_prompt, max_new_tokens=48),
            eng.generate(_prompt(11, 40), max_new_tokens=48,
                         temperature=0.9, top_k=12))
        await eng.stop()
        return a["tokens"], b["tokens"]
    a, b = asyncio.run(go())
    assert a == van[0]
    assert len(b) == 48 and all(0 <= t < cfg.vocab_size for t in b)


def test_spec_max_new_bound_mid_accept(tiny_model):
    """Finishing mid-accepted-draft (max_new hit) drops the surplus
    tail and still matches vanilla's truncated stream."""
    cfg, params = tiny_model
    prompt = _periodic_prompt(9)
    van, _ = _run_engine(cfg, params, [prompt], spec_on=False,
                         max_new=5)
    spc, _ = _run_engine(cfg, params, [prompt], spec_on=True,
                         max_new=5)
    assert spc == van and len(spc[0]) == 5


def test_spec_eos_mid_accept(tiny_model):
    """eos emitted inside an accepted run ends the request there."""
    cfg, params = tiny_model
    prompt = _periodic_prompt(9)
    van, _ = _run_engine(cfg, params, [prompt], spec_on=False)
    eos = van[0][10]    # a token known to appear mid-stream
    van_eos, _ = _run_engine(cfg, params, [prompt], spec_on=False,
                             eos_id=eos)
    spc_eos, _ = _run_engine(cfg, params, [prompt], spec_on=True,
                             eos_id=eos)
    assert spc_eos == van_eos
    assert spc_eos[0][-1] == eos and len(spc_eos[0]) <= len(van[0])


def test_spec_sampling_run_completes(tiny_model):
    """temperature>0 speculative decode: rejection-sampling acceptance
    end-to-end (distribution pinned in
    test_accept_rejection_sampling_preserves_distribution)."""
    cfg, params = tiny_model
    out, _ = _run_engine(cfg, params, [_periodic_prompt(9)],
                         spec_on=True, temperature=0.8, top_k=8,
                         max_new=32)
    assert len(out[0]) == 32
    assert all(0 <= t < cfg.vocab_size for t in out[0])


def test_spec_paged_flash_impl_parity(tiny_model):
    """Verify under kv_impl=paged_flash (decode runs the fused kernel
    through the interpreter on CPU; verify runs the gather-twin
    multi-query attention) matches the gather impl's greedy stream."""
    cfg, params = tiny_model
    prompt = _periodic_prompt(9)
    gather, _ = _run_engine(cfg, params, [prompt], spec_on=True,
                            max_new=12, kv_impl="gather")
    flash, _ = _run_engine(cfg, params, [prompt], spec_on=True,
                           max_new=12, kv_impl="paged_flash")
    assert flash == gather


def test_spec_knobs_read_from_config(tiny_model, monkeypatch):
    """spec_decode / spec_draft_tokens / spec_ngram_max /
    spec_backoff_window flow Config -> engine (spec=None reads the
    knobs; the kwarg overrides)."""
    cfg, params = tiny_model
    c = get_config()
    monkeypatch.setattr(c, "spec_decode", True)
    monkeypatch.setattr(c, "spec_draft_tokens", 2)
    monkeypatch.setattr(c, "spec_ngram_max", 2)
    monkeypatch.setattr(c, "spec_backoff_window", 8)

    async def go():
        eng = LLMEngine(cfg, params, max_slots=2, max_len=256,
                        prefill_buckets=(64,), cache_dtype="float32",
                        kv_block_size=16)
        assert eng._spec and eng._spec_k == 2
        assert eng._spec_buckets == (2, 3)
        assert eng._spec_ngram == 2 and eng._spec_window == 8
        out = await eng.generate(_periodic_prompt(9), max_new_tokens=8)
        r_off = LLMEngine(cfg, params, max_slots=2, max_len=256,
                          prefill_buckets=(64,), cache_dtype="float32",
                          kv_block_size=16, spec=False)
        assert not r_off._spec
        await eng.stop()
        await r_off.stop()
        return out["tokens"]
    toks = asyncio.run(go())
    van, _ = _run_engine(cfg, params, [_periodic_prompt(9)],
                         spec_on=False, max_new=8)
    assert toks == van[0]


# --- verify-width compile discipline (satellite) ----------------------


def test_verify_width_compile_discipline(tiny_model):
    """Varying accepted/drafted lengths must compile at most
    len(width_buckets) verify variants: widths pad UP to the bucket
    set, so devmon sees a bounded number of jit(paged_verify_steps)
    compiles and _JITS holds one entry per (geometry, width)."""
    from ray_tpu.util import events
    cfg, params = tiny_model
    before = [e for e in events.dump()
              if e.get("name") == "compile"
              and "paged_verify_steps" in str(e.get("fn"))]

    async def go():
        # unique max_len -> unique pool geometry -> cold verify jits
        eng = LLMEngine(cfg, params, max_slots=2, max_len=320,
                        prefill_buckets=(64, 128),
                        cache_dtype="float32",
                        kv_block_size=16, spec=True)
        # the draft budget is clamped by remaining max_new headroom, so
        # these requests exercise distinct verify widths: budget 4 ->
        # w=5, budget 2 -> w=3, budget 1 -> w=2. The tiny-horizon
        # requests re-prompt with the first request's (periodic by
        # then) output so the drafter matches at round one, before
        # max_new is spent
        prompt = _periodic_prompt(9)
        a = await eng.generate(prompt, max_new_tokens=24)
        await eng.generate(prompt + a["tokens"], max_new_tokens=4)
        await eng.generate(prompt + a["tokens"], max_new_tokens=3)
        pool_key = kc._pool_key(eng._pool)
        await eng.stop()
        return pool_key
    pool_key = asyncio.run(go())

    buckets = spec.width_buckets(int(get_config().spec_draft_tokens))
    widths = {k[1] for k in kc._JITS
              if k[0] == "paged_verify_steps"
              and tuple(k[2:2 + len(pool_key)]) == pool_key}
    assert widths == set(buckets)   # every bucket exercised, no extra
    after = [e for e in events.dump()
             if e.get("name") == "compile"
             and "paged_verify_steps" in str(e.get("fn"))]
    new = len(after) - len(before)
    assert new <= len(buckets), (new, buckets)


# --- adversarial prompts: graceful degradation ------------------------


def test_spec_low_hit_backs_off_and_matches_vanilla(tiny_model):
    """An adversarial low-hit prompt still exact-matches vanilla
    greedy, and the drafter's accept window drives rounds back to the
    vanilla block path (bounded verify overhead)."""
    cfg, params = tiny_model
    prompt = _prompt(5, 64)     # non-periodic, low n-gram hit
    van, _ = _run_engine(cfg, params, [prompt], spec_on=False)

    async def go():
        eng = LLMEngine(cfg, params, max_slots=2, max_len=256,
                        prefill_buckets=(64,), cache_dtype="float32",
                        kv_block_size=16, spec=True)
        out = await eng.generate(prompt, max_new_tokens=48)
        await eng.stop()
        return out["tokens"]
    spc = asyncio.run(go())
    assert spc == van[0]
