"""Wire codecs and error-feedback gradient sync: the _Int4Codec frame
format, ErrorFeedback residual accounting (train/collective.py +
train/zero.py), and the tuner's codec band behind
``allreduce_gradients(codec="auto")``.

Exercises the codec knob family by name so the metrics/knob lint can
pin it: ``collective_codec_error_bound``, ``collective_codec_min_bytes``
and ``codec_error_feedback`` (scripts/check_metrics_lint.py).

Named late in the alphabet ON PURPOSE: tier-1 is wall-clock bounded
(870s DOTS_PASSED cutoff) and new modules must not shift earlier
modules out of the window.
"""

from concurrent.futures import ThreadPoolExecutor

import numpy as np
import optax
import pytest

from ray_tpu.config import get_config
from ray_tpu.dag import ring as ring_mod
from ray_tpu.dag import tuner
from ray_tpu.dag.channel import ShmRingChannel
from ray_tpu.dag.ring import RingReducer
from ray_tpu.train.collective import ErrorFeedback, _ef_allreduce
from ray_tpu.train.zero import ShardedOptimizer


@pytest.fixture(autouse=True)
def _clean_tuner():
    tuner.invalidate()
    yield
    tuner.invalidate()


def _make_ring(n, **kw):
    chans = [ShmRingChannel(create=True, nslots=4, slot_bytes=1 << 20)
             for _ in range(n)]
    reds = [RingReducer(chans[r], chans[(r - 1) % n], rank=r, size=n,
                        timeout_s=10.0, **kw) for r in range(n)]
    try:
        yield reds
    finally:
        for c in chans:
            c.close()
            c.unlink()


def _all(reds, fn):
    with ThreadPoolExecutor(len(reds)) as ex:
        return list(ex.map(fn, reds))


def _wire_bytes():
    m = ring_mod.allreduce_metrics()
    return sum(m["bytes"]._values.values())


# ---------------------------------------------------------------- codec


def test_int4_codec_roundtrip_frame_properties():
    """The int4 frame: per-block scales + two values per byte. The
    round-trip error bound is scale/2 per element; packing handles odd
    lengths, zero-size payloads, exact zeros, and poisons non-finite
    blocks without leaking into neighbours."""
    from ray_tpu.dag.ring import _Int4Codec, codec_roundtrip
    c = _Int4Codec()
    rng = np.random.default_rng(0)
    for n in (1001, 1000, 1, 2):        # odd and even lengths
        x = rng.standard_normal(n).astype(np.float32) * 3.0
        frame = c.encode(x)
        back = c.decode(frame, n, np.dtype(np.float32))
        nb = -(-n // 256)
        assert len(frame) == 4 * nb + (n + 1) // 2
        # ~13% of the 4n fp32 bytes once blocks amortize the scales
        if n >= 1000:
            assert len(frame) <= 0.15 * 4 * n
        scale = np.abs(x).max() / 7.0
        assert float(np.abs(back - x).max()) <= scale / 2 + 1e-7
    # zero-size: empty frame, empty decode, max_scale 0
    assert c.decode(c.encode(np.empty(0, np.float32)), 0,
                    np.dtype(np.float32)).size == 0
    # an all-zero block encodes exactly (scale 0)
    z = np.zeros(300, np.float32)
    assert np.array_equal(c.decode(c.encode(z), 300,
                                   np.dtype(np.float32)), z)
    # a NaN poisons its WHOLE block and only its block
    x = np.ones(512, np.float32)
    x[3] = np.nan
    back = c.decode(c.encode(x), 512, np.dtype(np.float32))
    assert not np.isfinite(back[:256]).any()
    assert np.isfinite(back[256:]).all()
    # codec_roundtrip is the EF helper view of the same transform
    y = rng.standard_normal(700).astype(np.float32)
    assert np.array_equal(codec_roundtrip(y, "int4"),
                          c.decode(c.encode(y), 700,
                                   np.dtype(np.float32)))
    assert np.array_equal(codec_roundtrip(y, None), y)


def test_int4_flat_ring_bitwise_identity_error_gauge_and_wire_ratio():
    """int4 over the flat ring: every rank decodes the owner's frames
    verbatim (bitwise identity), the error gauge is labelled
    {codec="int4"}, and the reduce-scatter leg ships <= 0.25x the fp32
    allreduce bytes (the acceptance pin)."""
    n = 4
    gen = _make_ring(n)
    reds = next(gen)
    rng = np.random.default_rng(11)
    vals = [rng.standard_normal(5003).astype(np.float32)
            for _ in range(n)]
    c0 = _wire_bytes()
    _all(reds, lambda red: red.reduce(vals[red.rank], op="mean"))
    c1 = _wire_bytes()
    outs = _all(reds, lambda red: red.reduce(vals[red.rank], op="mean",
                                             quantize="int4"))
    c2 = _wire_bytes()
    for o in outs[1:]:
        assert np.array_equal(o, outs[0])
    exact = sum(v.astype(np.float64) for v in vals) / n
    err = float(np.abs(outs[0] - exact).max())
    bound = ring_mod.last_quant_error("int4")
    assert bound is not None and err <= bound + 1e-6
    assert 'codec="int4"' in \
        ring_mod.allreduce_metrics()["quant_err"].render()
    # int4 allreduce (RS + AG legs) vs fp32 allreduce; the RS leg alone
    # is about half of that, comfortably under the 0.25x pin
    assert (c2 - c1) <= 0.30 * (c1 - c0), (c2 - c1, c1 - c0)
    crs0 = _wire_bytes()
    _all(reds, lambda red: red.reduce_scatter(vals[red.rank], op="mean",
                                              quantize="int4"))
    crs1 = _wire_bytes()
    assert (crs1 - crs0) <= 0.25 * (c1 - c0), (crs1 - crs0, c1 - c0)
    gen.close()


def test_int4_zero_size_shards_and_non_float_rejection():
    """4 ranks, 2 elements: trailing ranks own zero-size shards and the
    int4 encode/decode path must pass empties through; integer payloads
    are rejected before any frame is cut."""
    gen = _make_ring(4)
    reds = next(gen)
    v = np.array([4.0, 8.0], np.float32)
    shards = _all(reds, lambda red: red.reduce_scatter(
        v, op="sum", quantize="int4"))
    # the total*r//n split leaves ranks 0 and 2 with zero-size shards
    assert [s.size for s in shards] == [0, 1, 0, 1]
    assert np.concatenate(shards).tolist() == [16.0, 32.0]
    with pytest.raises(TypeError, match="quantization requires"
                                        " floating-point"):
        _all(reds, lambda red: red.reduce(
            np.array([1, 2], np.int32), op="sum", quantize="int4"))
    gen.close()


# ------------------------------------------------------- error feedback


def test_error_feedback_residual_accounting():
    """ErrorFeedback unit: the residual is exactly wanted-minus-shipped,
    re-keys zero it (generation / layout / codec changes), invalidate
    drops it."""
    ef = ErrorFeedback()
    assert ef.ensure(gen=("g", 0), total=10, tag="int8") is True
    x = (np.arange(10, dtype=np.float32) - 5.0) * 0.01
    comp = ef.compensate(x)
    assert np.array_equal(comp, x)          # first round: residual 0
    ef.absorb(comp, "int8")
    from ray_tpu.dag.ring import codec_roundtrip
    want = comp - codec_roundtrip(comp, "int8")
    assert np.array_equal(ef.residual, want)
    assert float(np.abs(ef.residual).max()) > 0
    # same key: residual carries into the next compensate
    assert ef.ensure(gen=("g", 0), total=10, tag="int8") is False
    assert np.array_equal(ef.compensate(x), x + want)
    # ANY key component change provably zeroes the residual
    for gen, total, tag in ((("g", 1), 10, "int8"),
                            (("g", 1), 12, "int8"),
                            (("g", 1), 12, "int4")):
        assert ef.ensure(gen=gen, total=total, tag=tag) is True
        assert ef.residual.size == total
        assert not ef.residual.any()
        ef.absorb(np.full(total, 0.003, np.float32), tag)
    ef.invalidate()
    assert ef.residual is None and ef.key is None
    # offset slicing: bucketed absorb touches only its own slice
    ef.ensure(gen=("g", 2), total=8, tag="int4")
    seg = np.full(3, 0.005, np.float32)
    ef.absorb(seg, "int4", offset=5)
    assert not ef.residual[:5].any()
    assert np.array_equal(
        ef.residual[5:], seg - codec_roundtrip(seg, "int4"))


class _FakeCtx:
    """The slice of TrainContext that _ef_allreduce/_resolve_codec
    touch: identity for the residual key plus the wired ring."""

    def __init__(self, ring, group_id="test-group", generation=0):
        self._ring = ring
        self.group_id = group_id
        self.generation = generation

    def gradient_sync_ring(self):
        return self._ring

    def get_world_size(self):
        return self._ring.size


def test_ef_allreduce_residual_cancels_bias_over_rounds():
    """The EF property: with constant gradients plain int4 sync repeats
    the SAME quantization error every round (bias), while the carried
    residual dithers the compensated stream so the RUNNING MEAN of the
    synced gradient pulls well inside the no-EF error — bucketed and
    unbucketed, bitwise identical across ranks, residual visible on
    the context. (Ring hops re-quantize partial sums; that part is
    noise EF cannot see, so the pin is relative to no-EF, not zero.)"""
    n, size, rounds = 4, 2003, 16
    rng = np.random.default_rng(3)
    grads = [rng.standard_normal(size).astype(np.float32) * 0.1
             for _ in range(n)]
    exact = sum(g.astype(np.float64) for g in grads) / n
    # no-EF baseline: identical inputs, identical rounds -> the running
    # mean keeps the full one-round quantization error
    gen = _make_ring(n)
    reds = next(gen)
    base = _all(reds, lambda red: red.reduce(grads[red.rank], op="mean",
                                             quantize="int4"))
    noef_err = float(np.abs(np.asarray(base[0], np.float64)
                            - exact).max())
    gen.close()
    for bucket_bytes in (None, 2048):
        gen = _make_ring(n)
        reds = next(gen)
        ctxs = [_FakeCtx(red) for red in reds]

        def run(red):
            ctx = ctxs[red.rank]
            acc = np.zeros(size, np.float64)
            for _ in range(rounds):
                out = _ef_allreduce(ctx, {"w": grads[red.rank]}, "mean",
                                    "int4", bucket_bytes, None)
                acc += np.asarray(out["w"], np.float64)
            return acc / rounds, out["w"]

        outs = _all(reds, run)
        for avg, last in outs[1:]:
            assert np.array_equal(last, outs[0][1])
        avg_err = float(np.abs(outs[0][0] - exact).max())
        # ~1.9x better at 4 ranks (hop re-quantization sets the floor);
        # deterministic seeds, so 0.6 is a stable pin
        assert avg_err < 0.6 * noef_err, (avg_err, noef_err)
        assert ctxs[0]._grad_ef.residual is not None
        assert float(np.abs(ctxs[0]._grad_ef.residual).max()) > 0
        gen.close()


def test_zero_int4_error_feedback_tracks_fp32_trajectory():
    """ShardedOptimizer convergence contract: K sgd steps on constant
    gradients — int4+EF must land close to the fp32 trajectory, while
    int4 WITHOUT error feedback drifts by the accumulated quantization
    bias. codec_error_feedback=False (the Config default gate) must
    keep the accumulator off when error_feedback is unset."""
    n, lr, steps = 4, 0.05, 12
    rng = np.random.default_rng(9)
    params = {"w": rng.standard_normal(1003).astype(np.float32)}
    grads = [{"w": rng.standard_normal(1003).astype(np.float32)}
             for _ in range(n)]
    mean_g = sum(g["w"].astype(np.float64) for g in grads) / n
    fp32_w = params["w"].astype(np.float64) - lr * steps * mean_g

    def run(red, **kw):
        so = ShardedOptimizer(optax.sgd(lr), group=red, **kw)
        state = so.init(params)
        p = params
        for _ in range(steps):
            p, state = so.update(grads[red.rank], state, p)
        return p["w"], so

    for kw in ({"grad_quantize": "int4"},           # Config default: EF on
               {"grad_quantize": "int4", "error_feedback": True}):
        gen = _make_ring(n)
        outs = _all(next(gen), lambda red: run(red, **kw))
        gen.close()
        ef_w, so = outs[0]
        for w, _ in outs[1:]:
            assert np.array_equal(w, ef_w)
        assert so._ef is not None and so._ef.residual is not None
        ef_div = float(np.abs(ef_w - fp32_w).max())
        assert ef_div < 2 * lr * ring_mod.last_quant_error("int4"), ef_div

    gen = _make_ring(n)
    outs = _all(next(gen), lambda red: run(red, grad_quantize="int4",
                                           error_feedback=False))
    gen.close()
    noef_w, so = outs[0]
    assert so._ef is None
    noef_div = float(np.abs(noef_w - fp32_w).max())
    # without EF the per-step encode bias repeats every step; EF carries
    # it forward and lands measurably closer to the fp32 trajectory
    # (the floor is the ring's per-hop re-quantization, which per-rank
    # EF cannot see — deterministic seeds make 1.5x a stable pin)
    assert noef_div > 1.5 * ef_div, (noef_div, ef_div)

    # the Config gate: codec_error_feedback=False + error_feedback=None
    # leaves the accumulator off entirely
    cfg = get_config()
    saved = cfg.codec_error_feedback
    cfg.codec_error_feedback = False
    try:
        gen = _make_ring(n)
        outs = _all(next(gen), lambda red: run(red, grad_quantize="int4"))
        gen.close()
        assert outs[0][1]._ef is None
        assert np.array_equal(outs[0][0], noef_w)
    finally:
        cfg.codec_error_feedback = saved


def test_ef_residual_rekeys_across_group_size_change():
    """The N -> N-1 reshard contract on the optimizer's accumulator:
    a residual accumulated against the old split must never be read
    back against the new one — the (generation, ring size) key re-zeroes
    it, and reshard() invalidates eagerly even before the next step."""
    n = 4
    gen4 = _make_ring(n)
    reds4 = next(gen4)
    rng = np.random.default_rng(2)
    params = {"w": rng.standard_normal(903).astype(np.float32)}
    grads = [{"w": rng.standard_normal(903).astype(np.float32)}
             for _ in range(n)]

    def run(red):
        so = ShardedOptimizer(optax.sgd(0.1), group=red,
                              grad_quantize="int4", error_feedback=True)
        state = so.init(params)
        so.update(grads[red.rank], state, params)
        return so

    sos = _all(reds4, run)
    so = sos[0]
    old = so._ef.residual.copy()
    assert float(np.abs(old).max()) > 0
    old_key = so._ef.key
    # eager drop: reshard() calls invalidate() before any new-ring step
    so._ef.invalidate()
    assert so._ef.residual is None
    gen4.close()
    # even WITHOUT the eager drop, stepping on a 3-rank ring re-keys
    # (gen carries ring size) and provably zeroes the residual
    so2 = sos[1]
    assert so2._ef.key == old_key
    gen3 = _make_ring(3)
    reds3 = next(gen3)
    so2._g = reds3[1]
    so2._gen = -1               # pre-wired group: static generation
    ef = so2._ef_for(reds3[1], 903)
    assert ef is so2._ef and ef.key != old_key
    assert ef.residual.size == 903 and not ef.residual.any()
    gen3.close()


# ------------------------------------------------------- codec=auto


def test_choose_codec_switches_by_payload_error_and_ef_gate():
    """The auto-selection policy table: payloads under
    collective_codec_min_bytes stay fp32; a probed band picks the
    cheapest lossy codec under collective_codec_error_bound; the live
    error gauge overrides a stale probe; with error feedback off the
    lossy codecs are never chosen."""
    cfg = get_config()
    saved = (cfg.collective_codec_error_bound,
             cfg.collective_codec_min_bytes)
    try:
        cfg.collective_codec_min_bytes = 64 * 1024
        cfg.collective_codec_error_bound = 1e-2
        # no band probed yet: bf16 when EF can absorb, else fp32
        assert tuner.choose_codec(1 << 20, 4, key="g") == "bf16"
        assert tuner.choose_codec(1 << 20, 4, key="g",
                                  ef_enabled=False) == "fp32"
        tuner.register_codec_profile("g", 4, "int4", 1e-3, err=5e-3)
        tuner.register_codec_profile("g", 4, "int8", 2e-3, err=1e-3)
        tuner.register_codec_profile("g", 4, "bf16", 3e-3, err=0.0)
        tuner.register_codec_profile("g", 4, "fp32", 4e-3, err=0.0)
        # everything under the bound: int4 wins (cheapest wire)
        assert tuner.choose_codec(1 << 20, 4, key="g") == "int4"
        # small payload: scales never amortize, stay fp32
        assert tuner.choose_codec(1024, 4, key="g") == "fp32"
        assert tuner.choose_codec(None, 4, key="g") == "int4"
        # tighten the bound past int4's probed error: back off to int8
        cfg.collective_codec_error_bound = 2e-3
        assert tuner.choose_codec(1 << 20, 4, key="g") == "int8"
        # past both: bf16 (lossless-ish cast, no EF needed)
        cfg.collective_codec_error_bound = 1e-4
        assert tuner.choose_codec(1 << 20, 4, key="g") == "bf16"
        # the LIVE gauge trips the bound even when the probe looked ok
        cfg.collective_codec_error_bound = 1e-2
        assert tuner.choose_codec(
            1 << 20, 4, key="g",
            live_err={"int4": 0.5, "int8": 0.5}) == "bf16"
        # EF off: int4/int8 are unsafe regardless of the band
        assert tuner.choose_codec(1 << 20, 4, key="g",
                                  ef_enabled=False) == "bf16"
        # a different ring size is a different band
        assert tuner.choose_codec(1 << 20, 8, key="g") == "bf16"
    finally:
        (cfg.collective_codec_error_bound,
         cfg.collective_codec_min_bytes) = saved


def test_tuner_invalidate_clears_codec_band():
    """Ring-generation bumps call tuner.invalidate(); the cached codec
    choice must go with the impl cache or a pre-reshape band would keep
    electing a codec probed against a dead ring."""
    tuner.register_codec_profile("g1", 4, "int8", 1e-3, err=1e-3)
    tuner.register_codec_profile("g2", 4, "int8", 1e-3, err=1e-3)
    assert tuner.codec_profile_for("g1", 4) is not None
    tuner.invalidate("g1")
    assert tuner.codec_profile_for("g1", 4) is None
    assert tuner.codec_profile_for("g2", 4) is not None
    tuner.invalidate()
    assert tuner.codec_profile_for("g2", 4) is None
    # a re-registered band with a NEW size replaces the stale entry
    tuner.register_codec_profile("g1", 4, "int8", 1e-3, err=1e-3)
    tuner.register_codec_profile("g1", 3, "int4", 1e-3, err=1e-3)
    assert tuner.codec_profile_for("g1", 4) is None
    assert set(tuner.codec_profile_for("g1", 3)["codecs"]) == {"int4"}


def test_probe_codecs_records_band_on_live_rings():
    """probe_codecs is itself a collective: all ranks probe in lockstep
    and every rank lands the same band — wire times positive, quant
    errors straight off the labelled gauge."""
    gen = _make_ring(4)
    reds = next(gen)
    _all(reds, tuner.probe_codecs)
    band = tuner.codec_profile_for("", 4)
    assert band is not None and band["size"] == 4
    assert {"int4", "int8", "fp32"} <= set(band["codecs"])
    for tag, e in band["codecs"].items():
        assert e["round_s"] > 0
        assert e["err"] >= 0
        if tag in ("int4", "int8"):
            assert e["err"] > 0     # gaussian probe payload: lossy
    gen.close()


def test_resolve_codec_auto_on_live_rings_switches_by_knobs():
    """codec="auto" end to end over real rings: the probe round runs as
    a collective, then the choice flips with the error-bound and
    min-bytes knobs — the demonstrably-switches acceptance pin."""
    from ray_tpu.train.collective import _resolve_codec
    cfg = get_config()
    saved = (cfg.collective_codec_error_bound,
             cfg.collective_codec_min_bytes)
    gen = _make_ring(4)
    reds = next(gen)
    ctxs = [_FakeCtx(red) for red in reds]
    big = {"w": np.zeros(64 * 1024, np.float32)}    # 256 KiB payload
    try:
        cfg.collective_codec_min_bytes = 64 * 1024
        cfg.collective_codec_error_bound = 100.0    # everything passes
        tags = _all(reds, lambda red: _resolve_codec(
            ctxs[red.rank], big, "auto", True, None))
        assert tags == ["int4"] * 4
        # a small payload resolves from layout+config alone — fp32,
        # no agreement round, safe to call single-threaded
        assert _resolve_codec(ctxs[0], {"w": np.zeros(8, np.float32)},
                              "auto", True, None) == "fp32"
        # every other resolution is ITSELF a collective (the
        # live-error agreement round) — knob flips run on all ranks
        cfg.collective_codec_error_bound = 1e-9
        tags = _all(reds, lambda red: _resolve_codec(
            ctxs[red.rank], big, "auto", True, None))
        assert len(set(tags)) == 1 and tags[0] in ("bf16", "fp32")
        cfg.collective_codec_error_bound = 100.0
        tags = _all(reds, lambda red: _resolve_codec(
            ctxs[red.rank], big, "auto", False, None))
        assert len(set(tags)) == 1 and tags[0] in ("bf16", "fp32")
        # a concrete codec= passes straight through, no collective
        assert _resolve_codec(ctxs[0], big, "int8", True, None) == "int8"
    finally:
        (cfg.collective_codec_error_bound,
         cfg.collective_codec_min_bytes) = saved
        gen.close()


def test_resolve_codec_agrees_across_divergent_rank_local_state():
    """The cross-rank agreement contract: the live error gauge and the
    tuner's band cache are rank-local (each rank quantizes different
    partial sums; LRU eviction is per-process), so without agreement
    ranks near the error bound would resolve DIFFERENT codecs and feed
    the same collective mismatched wire options. The resolution round
    max-reduces those inputs: a hot gauge on ONE rank backs every rank
    off the lossy codec, and a band miss on ONE rank re-probes on all
    ranks in lockstep (the test completing without a ring timeout IS
    the lockstep assertion)."""
    import threading

    from ray_tpu.train.collective import _resolve_codec
    cfg = get_config()
    saved = (cfg.collective_codec_error_bound,
             cfg.collective_codec_min_bytes)
    gen = _make_ring(4)
    reds = next(gen)
    ctxs = [_FakeCtx(red) for red in reds]
    big = {"w": np.zeros(64 * 1024, np.float32)}
    tls = threading.local()
    orig_err = ring_mod.last_quant_error
    orig_prof = tuner.codec_profile_for

    def fake_err(tag):
        d = getattr(tls, "live", None)
        return d.get(tag, orig_err(tag)) if d else orig_err(tag)

    def fake_prof(key, size):
        if getattr(tls, "evicted", False):
            tls.evicted = False      # one miss, as an eviction would be
            return None
        return orig_prof(key, size)

    try:
        cfg.collective_codec_min_bytes = 64 * 1024
        cfg.collective_codec_error_bound = 1.0
        for tag, err in (("int4", 1e-6), ("int8", 1e-6),
                         ("bf16", 0.0), ("fp32", 0.0)):
            tuner.register_codec_profile("", 4, tag, 1e-3, err)
        ring_mod.last_quant_error = fake_err
        tuner.codec_profile_for = fake_prof

        def run_live(red):
            # rank 2's gauge alone trips the bound for int4
            tls.live = {"int4": 50.0} if red.rank == 2 else {"int4": 1e-6}
            tls.evicted = False
            return _resolve_codec(ctxs[red.rank], big, "auto", True, None)

        tags = _all(reds, run_live)
        assert tags == ["int8"] * 4, tags

        def run_evicted(red):
            tls.live = None
            tls.evicted = red.rank == 1
            return _resolve_codec(ctxs[red.rank], big, "auto", True, None)

        tags = _all(reds, run_evicted)
        assert len(set(tags)) == 1, tags
    finally:
        ring_mod.last_quant_error = orig_err
        tuner.codec_profile_for = orig_prof
        (cfg.collective_codec_error_bound,
         cfg.collective_codec_min_bytes) = saved
        gen.close()


def test_allreduce_gradients_codec_arg_single_worker_paths():
    """The public codec= arg at world size 1: validation still runs
    (competing selectors, unknown names, non-float payloads) but no
    ring is touched and the value comes back as-is."""
    from ray_tpu.train import api as train_api
    from ray_tpu.train.collective import allreduce_gradients
    ctx = train_api.TrainContext(rank=0, world_size=1, local_rank=0,
                                 node_rank=0, resume_checkpoint=None)
    train_api.set_context(ctx)
    try:
        g = {"w": np.arange(4, dtype=np.float32)}
        for codec in ("auto", "int4", "int8", "bf16", "fp32"):
            out = allreduce_gradients(g, codec=codec)
            assert np.array_equal(out["w"], g["w"])
        with pytest.raises(ValueError, match="competing wire"):
            allreduce_gradients(g, codec="int8", quantize="int8")
        with pytest.raises(ValueError, match="competing wire"):
            allreduce_gradients(g, codec="auto", wire_dtype="bfloat16")
        with pytest.raises(ValueError, match="codec must be one of"):
            allreduce_gradients(g, codec="int2")
        # op="mean" promotes ints to a float wire, so pin with op="sum"
        with pytest.raises(TypeError, match="floating-point"):
            allreduce_gradients({"w": np.arange(4)}, op="sum",
                                codec="int4")
    finally:
        train_api.set_context(None)
